"""Fleet (batched many-model) solver: masking, padding, consumer parity.

The parity discipline mirrors what XLA actually guarantees:

  * WITHIN one bucket program, a problem's result is BITWISE independent
    of its lane position and of which companions (real, dummy, fast,
    slow) ride along — that is what per-problem convergence masking in
    the batched carry means, and it is asserted to the byte.
  * ACROSS programs (fleet vs a separately-compiled solo solve), bitwise
    equality is not a property any XLA rewrite preserves (the batched
    program gets different fma/fusion decisions), so fleet-vs-loop
    parity is gated at the solution level: EXACT SV-identity sets,
    exact statuses, b/alpha within the cross-engine band the repo's
    other solver-parity suites use.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, blobs, rings
from tpusvm.fleet import (
    bucket_for,
    fleet_opt_errors,
    fleet_smo_solve,
    fleet_train,
    pack_problems,
    unpack_results,
)
from tpusvm.fleet.results import fleet_convergence_summary
from tpusvm.oracle import get_sv_indices
from tpusvm.solver import blocked_smo_solve
from tpusvm.status import Status


def _data(gen, **kw):
    X, Y = gen(**kw)
    return MinMaxScaler().fit_transform(X), Y


@pytest.fixture(scope="module")
def rings_problem():
    Xs, Y = _data(rings, n=256, seed=5)
    return jnp.asarray(Xs, jnp.float32), np.asarray(Y)


KW = dict(q=64, accum_dtype=jnp.float64)


# ------------------------------------------------------------- bucketing
def test_bucket_for_powers_of_two():
    assert [bucket_for(b) for b in (1, 2, 3, 5, 8, 9, 16, 17)] == \
        [1, 2, 4, 8, 8, 16, 16, 32]
    with pytest.raises(ValueError):
        bucket_for(0)


def test_pack_validation_errors(rings_problem):
    _, Y = rings_problem
    with pytest.raises(ValueError, match="empty problem list"):
        pack_problems([], [], [])
    with pytest.raises(ValueError, match="C values"):
        pack_problems([Y], [1.0, 2.0], [1.0])
    with pytest.raises(ValueError, match="positive finite"):
        pack_problems([Y], [-1.0], [1.0])
    with pytest.raises(ValueError, match="outside"):
        pack_problems([np.full_like(Y, 2)], [1.0], [1.0])
    with pytest.raises(ValueError, match="zero labels on live rows"):
        y0 = Y.copy()
        y0[3] = 0
        pack_problems([y0], [1.0], [1.0])
    # zero labels on MASKED rows are the padding idiom and pack fine
    valid = np.ones(len(Y), bool)
    valid[3] = False
    y0 = Y.copy()
    y0[3] = 0
    batch = pack_problems([y0], [1.0], [1.0], valids=[valid])
    assert batch.bucket == 1 and batch.n_problems == 1
    with pytest.raises(ValueError, match="power of two"):
        pack_problems([Y, Y, Y], [1.0] * 3, [1.0] * 3, bucket=3)


def test_unsupported_fleet_opts_rejected(rings_problem):
    X, Y = rings_problem
    for bad in (dict(krow_cache=64), dict(inner="pallas"),
                dict(shrink_stable=3), dict(fused_fupdate=True)):
        with pytest.raises(ValueError, match="not fleet-compatible"):
            fleet_train(X, [Y], [10.0], [10.0], **bad, **KW)
    # the same knobs at their inert defaults pass through silently
    assert fleet_opt_errors(dict(inner="xla", krow_cache=0)) == []


# ------------------------------------- masking / padding / lane invariance
def test_companion_and_lane_invariance_bitwise(rings_problem):
    """A problem's lane is bit-identical no matter who shares the bucket:
    the hard no-crosstalk gate per-problem convergence masking implies.
    The FAST problems (the flipped/loose ones) freeze lanes while the
    slow problem keeps iterating — their carries must not move."""
    X, Y = rings_problem
    P, Q = Y, -Y
    D = np.zeros_like(Y)  # inert dummy
    r1 = fleet_smo_solve(X, jnp.asarray(np.stack([P, Q])),
                         Cs=jnp.asarray([10.0, 1.0]),
                         gammas=jnp.asarray([10.0, 5.0]), **KW)
    r2 = fleet_smo_solve(X, jnp.asarray(np.stack([P, D])),
                         Cs=jnp.asarray([10.0, 1.0]),
                         gammas=jnp.asarray([10.0, 5.0]), **KW)
    r3 = fleet_smo_solve(X, jnp.asarray(np.stack([Q, P])),
                         Cs=jnp.asarray([1.0, 10.0]),
                         gammas=jnp.asarray([5.0, 10.0]), **KW)
    a = np.asarray(r1.alpha[0])
    assert np.array_equal(a, np.asarray(r2.alpha[0]))      # companions
    assert np.array_equal(a, np.asarray(r3.alpha[1]))      # lane position
    assert float(r1.b[0]) == float(r2.b[0]) == float(r3.b[1])
    assert int(r1.n_outer[0]) == int(r3.n_outer[1])


def test_padding_lanes_provably_inert(rings_problem):
    """Dummy zero-y problems: converged-at-entry masks (both Keerthi
    index sets empty), NO_WORKING_SET after one masked iteration, alpha
    identically zero — and the real problems bitwise unaffected."""
    X, Y = rings_problem
    res = fleet_train(X, [Y, -Y, Y], [10.0, 1.0, 5.0], [10.0, 5.0, 2.0],
                      **KW)  # B=3 -> bucket 4, one dummy lane
    raw = fleet_smo_solve(
        X, jnp.asarray(np.stack([Y, -Y, Y, np.zeros_like(Y)])),
        Cs=jnp.asarray([10.0, 1.0, 5.0, 1.0]),
        gammas=jnp.asarray([10.0, 5.0, 2.0, 1.0]), **KW)
    # the dummy lane is inert
    assert int(raw.status[3]) == Status.NO_WORKING_SET
    assert int(raw.n_iter[3]) == 1
    assert (np.asarray(raw.alpha[3]) == 0).all()
    # and fleet_train's unpacking returns the real lanes bitwise
    for i, r in enumerate(unpack_results(raw, 3)):
        assert np.array_equal(np.asarray(r.alpha),
                              np.asarray(res[i].alpha))


def test_fast_problem_frozen_next_to_slow_matches_solo(rings_problem):
    """The satellite's masking gate: an easy problem that converges in
    round 1 rides with a slow one; its lane must equal (bitwise) the
    same problem next to an inert dummy, and its SOLUTION must equal its
    solo blocked solve (exact SV ids, oracle-band b/alpha)."""
    X, Y = rings_problem
    # the warm-started easy lane: its own solved alphas — converges at
    # the first global check while the cold hard lane keeps running
    solo = blocked_smo_solve(X, jnp.asarray(Y), C=10.0, gamma=10.0, **KW)
    seed = np.asarray(solo.alpha)
    fast_slow = fleet_train(
        X, [Y, -Y], [10.0, 1.0], [10.0, 5.0],
        alpha0s=[seed, None], **KW)
    fast_dummy = fleet_smo_solve(
        X, jnp.asarray(np.stack([Y, np.zeros_like(Y)])),
        jnp.ones((2, len(Y)), bool),
        jnp.asarray(np.stack([seed, np.zeros_like(seed)])),
        Cs=jnp.asarray([10.0, 1.0]), gammas=jnp.asarray([10.0, 5.0]),
        warm_start=True, **KW)
    fast = fast_slow[0]
    assert int(fast.status) == Status.CONVERGED
    assert int(fast.n_iter) == 1  # converged at the first global check
    assert np.array_equal(np.asarray(fast.alpha),
                          np.asarray(fast_dummy.alpha[0]))
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(fast.alpha)), get_sv_indices(seed))
    np.testing.assert_allclose(np.asarray(fast.alpha), seed, atol=1e-10)


def test_fleet_vs_solo_solution_parity(rings_problem):
    """Cross-program parity: exact SV-identity sets and statuses, the
    cross-engine tolerance band on b/alpha (bitwise is a same-program
    property — see module docstring)."""
    X, Y = rings_problem
    problems = [(Y, 10.0, 10.0), (-Y, 1.0, 5.0), (Y, 5.0, 2.0)]
    fl = fleet_train(X, [p[0] for p in problems],
                     [p[1] for p in problems], [p[2] for p in problems],
                     **KW)
    for (y, C, g), r in zip(problems, fl):
        solo = blocked_smo_solve(X, jnp.asarray(y), C=C, gamma=g, **KW)
        assert int(r.status) == int(solo.status) == Status.CONVERGED
        np.testing.assert_array_equal(
            get_sv_indices(np.asarray(r.alpha)),
            get_sv_indices(np.asarray(solo.alpha)))
        np.testing.assert_allclose(float(r.b), float(solo.b), atol=1e-4)
        np.testing.assert_allclose(np.asarray(r.alpha),
                                   np.asarray(solo.alpha), atol=1e-3)


def test_compaction_is_solution_exact(rings_problem):
    """The segment driver (compact_every > 0) harvests converged lanes
    and re-buckets survivors; every problem's solution must match the
    monolithic launch at the solution level and the loop's SV sets
    exactly."""
    X, Y = rings_problem
    rng = np.random.default_rng(0)
    B = 6
    Cs = [float(c) for c in rng.choice([0.5, 1.0, 5.0, 10.0], B)]
    gs = [float(g) for g in rng.choice([2.0, 5.0, 10.0], B)]
    mono = fleet_train(X, [Y] * B, Cs, gs, **KW)
    comp = fleet_train(X, [Y] * B, Cs, gs, compact_every=3, **KW)
    for m, c in zip(mono, comp):
        assert int(m.status) == int(c.status) == Status.CONVERGED
        np.testing.assert_array_equal(
            get_sv_indices(np.asarray(m.alpha)),
            get_sv_indices(np.asarray(c.alpha)))
        np.testing.assert_allclose(float(m.b), float(c.b), atol=1e-4)


def test_valid_mask_padding_rows(rings_problem):
    """Per-problem valid masks ride the problem axis: rows masked out of
    one lane can stay live in another."""
    X, Y = rings_problem
    n = len(Y)
    valid = np.ones(n, bool)
    valid[200:] = False
    y_masked = Y.copy()
    y_masked[200:] = 0
    res = fleet_train(X, [y_masked, Y], [10.0, 10.0], [10.0, 10.0],
                      valids=[valid, None], **KW)
    assert (np.asarray(res[0].alpha)[200:] == 0).all()
    solo = blocked_smo_solve(X[:200], jnp.asarray(Y[:200]), C=10.0,
                             gamma=10.0, **KW)
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(res[0].alpha)[:200]),
        get_sv_indices(np.asarray(solo.alpha)))


# ----------------------------------------------------- telemetry + results
def test_per_problem_telemetry_and_summary(rings_problem):
    X, Y = rings_problem
    res = fleet_train(X, [Y, -Y], [10.0, 1.0], [10.0, 5.0],
                      telemetry=8, **KW)
    for r in res:
        assert r.telemetry is not None
        assert int(r.telemetry.count) == int(r.n_outer) + 1
    summary = fleet_convergence_summary(res)
    assert summary["problems"] == 2
    assert summary["converged"] == 2
    assert summary["statuses"] == ["CONVERGED", "CONVERGED"]
    assert summary["telemetry_rounds"] == [int(r.telemetry.count)
                                           for r in res]


def test_one_compile_per_bucket_across_cg_sweep(rings_problem):
    """The launch-economics acceptance gate, CPU-checkable: per-problem
    (C, gamma) are arrays, so a whole sweep at one bucket is ONE compile
    (prof recompile counter stays 0 after warmup)."""
    from tpusvm.obs import prof
    from tpusvm.obs.registry import MetricsRegistry

    X, Y = rings_problem
    Ys = jnp.asarray(np.stack([Y, -Y]))
    with prof.profiling(registry=MetricsRegistry()) as obs:
        for (c, g) in [(10.0, 10.0), (3.0, 5.0), (1.0, 2.0)]:
            res = fleet_smo_solve(X, Ys, Cs=jnp.asarray([c, c]),
                                  gammas=jnp.asarray([g, g]), **KW)
            np.asarray(res.alpha)
        compiles = [r for r in obs.records
                    if r["executable"] == "solver.fleet_smo_solve"]
    assert len(compiles) == 1


# --------------------------------------------------------- OvR consumer
def test_ovr_fleet_vs_loop_parity_fuzz():
    """The OvR consumer gate on a small fuzz corpus: solver='fleet'
    reproduces solver='blocked' head for head — exact SV-ID sets, equal
    statuses and held-out accuracy, b within the cross-engine band."""
    from tpusvm.data.synthetic import (
        BENCH_NOISE_MULTICLASS,
        mnist_like_multiclass,
    )
    from tpusvm.models import OneVsRestSVC

    for seed in (3, 11):
        X, labels = mnist_like_multiclass(
            n=460, d=32, noise=BENCH_NOISE_MULTICLASS, seed=seed)
        Xtr, ytr = X[:400], labels[:400]
        Xte, yte = X[400:], labels[400:]
        cfg = SVMConfig(C=10.0, gamma=1.0 / 32)
        opts = dict(q=64)
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            loop = OneVsRestSVC(config=cfg, solver="blocked",
                                solver_opts=opts).fit(Xtr, ytr)
            fleet = OneVsRestSVC(config=cfg, solver="fleet",
                                 solver_opts=opts).fit(Xtr, ytr)
        assert (loop.statuses_ == fleet.statuses_).all()
        # identical SV unions AND identical per-head coefficients'
        # support pattern = exact per-head SV-ID parity
        assert np.array_equal(loop.X_sv_, fleet.X_sv_)
        assert np.array_equal(loop.coef_ != 0, fleet.coef_ != 0)
        np.testing.assert_allclose(loop.b_, fleet.b_, atol=1e-3)
        assert loop.score(Xte, yte) == fleet.score(Xte, yte)


def test_ovr_blocked_loop_shares_hoisted_norms():
    """The satellite fix: the blocked host loop passes one shared sn=
    into every head's solve (asserted by spying the solver call)."""
    import tpusvm.models.ovr as ovr_mod

    Xs, Y = _data(blobs, n=120, d=6, seed=7)
    labels = np.where(Y > 0, 1, 0)
    seen = []
    import tpusvm.solver.blocked as blocked_mod

    orig = blocked_mod.blocked_smo_solve

    def spy(X, y, *a, **kw):
        seen.append(kw.get("sn"))
        return orig(X, y, *a, **kw)

    from unittest import mock

    from tpusvm.models import OneVsRestSVC

    with mock.patch.object(blocked_mod, "blocked_smo_solve", spy):
        # ovr imports the symbol inside fit, so patch the module it
        # imports FROM
        OneVsRestSVC(config=SVMConfig(C=1.0, gamma=0.5),
                     solver="blocked",
                     solver_opts=dict(q=32)).fit(Xs, labels)
    assert len(seen) == 2  # one call per class
    assert all(s is not None for s in seen)
    assert all(s is seen[0] for s in seen)  # ONE shared array
    del ovr_mod


# --------------------------------------------------------- tune consumer
def test_tune_fleet_vs_sequential_identical_winner_and_table():
    """The tune consumer gate: fleet dispatch reproduces the sequential
    path's winner AND the whole CV table (cold fits, so both paths
    solve identical problems)."""
    from tpusvm.tune import TuneConfig, make_grid, tune

    Xs, Y = _data(rings, n=240, seed=5)
    grid = make_grid([1.0, 8.0], [1.0, 8.0])
    for schedule in ("grid", "halving"):
        seq = tune(Xs, Y, grid,
                   TuneConfig(folds=2, schedule=schedule, min_rung=64,
                              warm_start=False),
                   base=SVMConfig())
        fl = tune(Xs, Y, grid,
                  TuneConfig(folds=2, schedule=schedule, min_rung=64,
                             warm_start=False, fleet=True),
                  base=SVMConfig())
        assert fl.winner == seq.winner
        assert fl.fleet and not seq.fleet
        for a, b in zip(seq.points, fl.points):
            assert a["status"] == b["status"]
            assert a["cv_accuracy"] == b["cv_accuracy"]
            assert a["fold_accuracy"] == b["fold_accuracy"]
            assert a["sv_count"] == b["sv_count"]


def test_tune_fleet_rejects_patience():
    from tpusvm.tune import TuneConfig

    with pytest.raises(ValueError, match="patience"):
        TuneConfig(fleet=True, patience=2)


def test_tune_fleet_warm_halving_runs():
    """Warm fleet halving: previous-rung seeds feed each lane; the run
    completes with a sane winner (trajectory differs from sequential
    warm by design — no same-rung neighbour seeding)."""
    from tpusvm.tune import TuneConfig, make_grid, tune

    Xs, Y = _data(rings, n=240, seed=5)
    grid = make_grid([1.0, 8.0], [1.0, 8.0])
    res = tune(Xs, Y, grid,
               TuneConfig(folds=2, schedule="halving", min_rung=64,
                          warm_start=True, fleet=True, fleet_compact=4),
               base=SVMConfig())
    assert res.winner["cv_accuracy"] > 0.9
    final = [p for p in res.points if p["status"] == "EVALUATED"]
    assert final  # the last rung evaluated its survivors
    # rung > 0 fits found previous-rung seeds
    assert any(p["warm_seeded"] > 0 for p in res.points
               if p["rung"] > 0)
