"""Tests for tpusvm.analysis.conc — the two-armed concurrency auditor.

Static arm: every JXC rule fires on its known-bad corpus snippet under
tests/analysis_corpus/conc/ (and on nothing else in the corpus), the
per-class model extraction is right, guarded-by suppressions document
their invariant, the baseline grandfathers, and the repo itself lints
conc-clean against the committed EMPTY baseline.

Dynamic arm: the schedule perturber is deterministic by seed (same seed
=> byte-identical schedule log), the four real-object invariant suites
pass, and the deliberately racy fixture is provably CAUGHT with the
reproducing seed reported.
"""

import json
import threading
import time
from pathlib import Path

import pytest

from tpusvm.analysis.conc import (
    CONC_RULE_SUMMARIES,
    all_conc_rules,
    conc_lint_file,
    conc_lint_paths,
    conc_lint_source,
)

REPO = Path(__file__).resolve().parent.parent
CORPUS = REPO / "tests" / "analysis_corpus" / "conc"
CONC_RULE_IDS = ("JXC201", "JXC202", "JXC203", "JXC204", "JXC205",
                 "JXC206")


# ---------------------------------------------------------------- registry
def test_registry_has_all_conc_rules():
    rules = all_conc_rules()
    assert tuple(sorted(rules)) == CONC_RULE_IDS
    for rid, rule in rules.items():
        assert rule.id == rid
        assert rule.summary
    assert set(CONC_RULE_SUMMARIES) == set(CONC_RULE_IDS)


def test_unknown_select_is_rejected():
    with pytest.raises(ValueError, match="unknown conc rule"):
        conc_lint_source("x = 1\n", select={"JXC999"})


# ------------------------------------------------------------------ corpus
@pytest.mark.parametrize("rule_id", CONC_RULE_IDS)
def test_rule_fires_on_its_corpus_snippet(rule_id):
    matches = sorted(CORPUS.glob(f"{rule_id.lower()}_*.py"))
    assert matches, f"no conc corpus file for {rule_id}"
    findings, _ = conc_lint_file(matches[0])
    fired = {f.rule for f in findings}
    assert rule_id in fired, (
        f"{rule_id} did not fire on {matches[0].name}; got {fired}"
    )
    # single-hazard by construction: a precision regression in ANY rule
    # shows up as an extra id here
    assert fired == {rule_id}, (
        f"extra rules fired on {matches[0].name}: {fired - {rule_id}}"
    )


def test_clean_corpus_is_clean():
    findings, suppressed = conc_lint_file(CORPUS / "clean.py")
    assert findings == []
    assert suppressed == []


def test_corpus_findings_are_located():
    for f in CORPUS.glob("jxc*.py"):
        findings, _ = conc_lint_file(f)
        for finding in findings:
            assert finding.line >= 1 and finding.col >= 1
            assert finding.snippet
            assert finding.fingerprint and len(finding.fingerprint) == 12


def test_parse_failure_is_a_finding():
    findings, _ = conc_lint_source("def broken(:\n")
    assert [f.rule for f in findings] == ["JXC200"]


# ----------------------------------------------------------- model extraction
def _model(src: str):
    from tpusvm.analysis.conc.model import ConcModel
    from tpusvm.analysis.context import ModuleContext

    return ConcModel(ModuleContext("<test>", src))


_MODEL_SRC = """
import queue
import threading as T


class W:
    def __init__(self):
        self._lock = T.Lock()
        self._sem = T.Semaphore(3)
        self._ev = T.Event()
        self._cond = T.Condition()
        self._q = queue.Queue()
        self.state = 0
        self._t = T.Thread(target=self._run, daemon=True)

    def _run(self):
        self._helper()

    def _helper(self):
        with self._lock:
            self.state += 1

    def client(self):
        return self.state
"""


def test_model_fields_and_kinds():
    m = _model(_MODEL_SRC)
    (cm,) = m.classes
    assert cm.name == "W"
    assert cm.sync_fields == {"_lock": "lock", "_sem": "semaphore",
                              "_ev": "event", "_cond": "condition"}
    assert cm.queue_fields == {"_q"}
    assert cm.thread_fields == {"_t"}
    assert cm.thread_targets == {"_run"}
    assert cm.spawns_threads
    assert set(cm.init_attrs) >= {"_lock", "_q", "state", "_t"}
    # worker closure: the helper called from the thread target is
    # worker-side too
    assert cm.worker_methods == {"_run", "_helper"}
    assert m.module_attr_kinds["_ev"] == "event"


def test_guarded_write_is_not_flagged():
    # _helper's write is under `with self._lock:` => no JXC201 even in a
    # thread-spawning class
    findings, _ = conc_lint_source(_MODEL_SRC)
    assert findings == []


def test_unguarded_write_is_flagged_with_side():
    src = _MODEL_SRC.replace(
        "        with self._lock:\n            self.state += 1",
        "        self.state += 1")
    findings, _ = conc_lint_source(src)
    assert [f.rule for f in findings] == ["JXC201"]
    assert "worker-side" in findings[0].message


def test_check_then_act_recheck_is_exempt():
    # double-checked pattern: the later block re-tests the attr under
    # the reacquired lock — the correct spelling, not a finding
    src = """
import threading


class B:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 10

    def take(self):
        with self._lock:
            ok = self.n > 0
        if ok:
            with self._lock:
                if self.n > 0:
                    self.n -= 1
        return ok
"""
    findings, _ = conc_lint_source(src)
    assert findings == []


def test_condition_wait_in_while_is_clean_and_bare_is_not():
    base = """
import threading


class G:
    def __init__(self):
        self._cond = threading.Condition()
        self.ready = False

    def wait_ready(self):
        with self._cond:
            {body}
"""
    good = base.format(body="while not self.ready:\n"
                            "                self._cond.wait()")
    findings, _ = conc_lint_source(good)
    assert findings == []
    bad = base.format(body="self._cond.wait()")
    findings, _ = conc_lint_source(bad)
    assert [f.rule for f in findings] == ["JXC206"]


def test_thread_joined_in_scope_is_owned():
    src = """
import threading


def run_all(fns):
    ts = [threading.Thread(target=f) for f in fns]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
"""
    findings, _ = conc_lint_source(src)
    assert findings == []


# ------------------------------------------------------------- suppression
_RACY_SRC = """
import threading


class W:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self.flag = False

    def _run(self):
        {line}
"""


def test_guarded_by_annotation_suppresses_and_documents():
    src = _RACY_SRC.format(
        line="self.flag = True  "
             "# tpusvm: guarded-by=one-way latch, GIL-atomic store")
    findings, suppressed = conc_lint_source(src)
    assert findings == []
    assert [f.rule for f in suppressed] == ["JXC201"]


def test_empty_guarded_by_does_not_suppress():
    src = _RACY_SRC.format(line="self.flag = True  # tpusvm: guarded-by=")
    findings, _ = conc_lint_source(src)
    assert [f.rule for f in findings] == ["JXC201"]


def test_standalone_guarded_by_line_applies_below():
    src = _RACY_SRC.format(
        line="# tpusvm: guarded-by=latch\n        self.flag = True")
    findings, suppressed = conc_lint_source(src)
    assert findings == [] and len(suppressed) == 1


def test_disable_comment_also_works():
    src = _RACY_SRC.format(
        line="self.flag = True  # tpusvm: disable=JXC201")
    findings, suppressed = conc_lint_source(src)
    assert findings == [] and len(suppressed) == 1


def test_file_level_disable():
    src = "# tpusvm: disable-file=JXC201\n" + _RACY_SRC.format(
        line="self.flag = True")
    findings, suppressed = conc_lint_source(src)
    assert findings == [] and len(suppressed) == 1


# ---------------------------------------------------------------- baseline
def test_baseline_grandfathers_conc_findings(tmp_path):
    from tpusvm.analysis.baseline import load_baseline, write_baseline

    target = CORPUS / "jxc201_unguarded_write.py"
    findings, _ = conc_lint_file(target)
    assert findings
    bl = tmp_path / "conc_bl.json"
    write_baseline(bl, findings)
    result = conc_lint_paths([str(target)], baseline=load_baseline(bl))
    assert result.findings == []
    assert len(result.baselined) == len(findings)
    assert result.exit_code == 0


def test_committed_conc_baseline_is_empty():
    from tpusvm.analysis.baseline import load_baseline

    path = REPO / ".tpusvm-conc-baseline.json"
    assert path.exists(), "committed conc baseline is missing"
    assert load_baseline(path) == set(), (
        "the conc baseline must stay EMPTY — fix findings or suppress "
        "them with a documented guarded-by annotation"
    )


# ---------------------------------------------------------- repo conc gate
def test_repo_lints_conc_clean():
    """The CI conc gate, in-process: the repo's own trees produce zero
    unsuppressed JXC findings (benign latches carry guarded-by
    annotations naming their invariant)."""
    result = conc_lint_paths(
        [str(REPO / "tpusvm"), str(REPO / "benchmarks"),
         str(REPO / "scripts"), str(REPO / "bench.py")])
    assert result.findings == [], "\n".join(
        f.render() for f in result.findings)
    assert result.files_scanned > 50
    # the documented latches in batcher/reader stay suppressed, not gone
    assert len(result.suppressed) >= 5


# --------------------------------------------------------------------- CLI
def test_cli_conc_dispatch_and_exit_codes(capsys):
    from tpusvm.analysis.cli import main

    rc = main(["conc", str(CORPUS / "jxc203_blocking_under_lock.py"),
               "--no-baseline"])
    assert rc == 1
    assert "JXC203" in capsys.readouterr().out
    rc = main(["conc", str(CORPUS / "clean.py"), "--no-baseline"])
    assert rc == 0


def test_cli_conc_json_schema(capsys):
    from tpusvm.analysis.cli import main

    rc = main(["conc", str(CORPUS / "jxc201_unguarded_write.py"),
               "--format", "json", "--no-baseline"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "tpusvm.analysis.conc"
    assert set(doc["rules"]) == set(CONC_RULE_IDS)
    assert doc["counts"]["JXC201"] == len(doc["findings"])
    for f in doc["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "snippet", "fingerprint"}


def test_cli_conc_list_rules(capsys):
    from tpusvm.analysis.cli import main

    assert main(["conc", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in CONC_RULE_IDS:
        assert rid in out


def test_cli_main_list_rules_includes_conc(capsys):
    from tpusvm.analysis.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "JXC201" in out and "[conc]" in out


def test_cli_conc_stress_list_suites(capsys):
    from tpusvm.analysis.cli import main

    assert main(["conc-stress", "--list-suites"]) == 0
    out = capsys.readouterr().out
    for suite in ("registry", "batcher", "reader", "breaker", "racy"):
        assert suite in out


def test_cli_conc_stress_unknown_suite_is_usage_error(capsys):
    from tpusvm.analysis.cli import main

    assert main(["conc-stress", "--suite", "nope"]) == 2


# ------------------------------------------------------------- perturber
def test_perturber_plan_is_deterministic_by_seed():
    from tpusvm.analysis.conc.stress import SchedulePerturber

    a = SchedulePerturber(7).plan_lines(("x", "y"), 64)
    b = SchedulePerturber(7).plan_lines(("x", "y"), 64)
    assert "\n".join(a) == "\n".join(b)  # byte-identical schedule log
    c = SchedulePerturber(8).plan_lines(("x", "y"), 64)
    assert a != c
    # decisions are pure functions of (seed, site, k): consuming events
    # in any thread order cannot change the plan
    p = SchedulePerturber(7)
    for _ in range(10):
        p.perturb("x")
    assert p.plan_lines(("x", "y"), 64) == a


def test_stress_report_schedule_is_byte_identical_across_runs():
    from tpusvm.analysis.conc.stress import stress_racy

    r1 = stress_racy(seed=3, iters=10, threads=2)
    r2 = stress_racy(seed=3, iters=10, threads=2)
    assert "\n".join(r1.schedule) == "\n".join(r2.schedule)
    assert r1.seed == r2.seed == 3


# ----------------------------------------------------- invariant suites
def test_stress_registry_suite_clean():
    from tpusvm.analysis.conc.stress import stress_registry

    rep = stress_registry(seed=0, iters=150, threads=4)
    assert rep.ok, rep.violations
    assert rep.events  # the perturber actually fired


def test_stress_batcher_suite_clean():
    from tpusvm.analysis.conc.stress import stress_batcher

    rep = stress_batcher(seed=0, iters=20, threads=4)
    assert rep.ok, rep.violations


def test_stress_reader_suite_clean():
    from tpusvm.analysis.conc.stress import stress_reader

    rep = stress_reader(seed=0, n_shards=10, depth=2)
    assert rep.ok, rep.violations


def test_stress_breaker_suite_clean():
    from tpusvm.analysis.conc.stress import stress_breaker

    rep = stress_breaker(seed=0, iters=100, threads=4)
    assert rep.ok, rep.violations


def test_racy_fixture_is_caught_and_seed_reported():
    """The acceptance gate: the harness must DEMONSTRABLY catch the
    seeded racy fixture, and the report must carry the reproducing
    seed."""
    from tpusvm.analysis.conc.stress import self_test

    rep = self_test()
    assert rep is not None, (
        "no seed in 0..7 caught the racy fixture — the perturber is "
        "not amplifying races"
    )
    assert rep.violations and "lost" in rep.violations[0]
    rendered = rep.render()
    assert f"--seed {rep.seed}" in rendered  # reproduce-by-seed line
    assert "reproduce" in rendered


def test_registry_snapshot_mid_write_is_mergeable():
    """The obs/registry satellite, asserted directly: a snapshot taken
    while writers are mid-flight is internally consistent (one lock
    acquisition covers every metric) and merges cleanly."""
    from tpusvm.analysis.conc.stress import PerturbLock, SchedulePerturber
    from tpusvm.obs.registry import MetricsRegistry, merge_snapshots

    p = SchedulePerturber(1)
    reg = MetricsRegistry()
    reg._lock = PerturbLock(p, "registry.lock", inner=reg._lock)
    c = reg.counter("mid.hits")
    h = reg.histogram("mid.lat", bounds=(1.0, 2.0))
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            c.inc()
            h.observe(1.5)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        snaps = []
        while len(snaps) < 20 and time.monotonic() < deadline:
            snaps.append(reg.snapshot())
    finally:
        stop.set()
        t.join(timeout=10.0)
    assert snaps
    for s in snaps:
        for e in s["metrics"]:
            if e["type"] == "histogram":
                assert sum(e["counts"]) == e["count"], "torn snapshot"
        merged = merge_snapshots(s, s)  # self-merge doubles counters
        for e, m in zip(s["metrics"], merged["metrics"]):
            if e["type"] == "counter":
                assert m["value"] == 2 * e["value"]


def test_serve_metrics_snapshot_single_acquisition_parity():
    """serve.Metrics.snapshot derives every counter from ONE registry
    snapshot; the values must match the per-metric reads exactly."""
    from tpusvm.serve.metrics import Metrics

    m = Metrics(buckets=(1, 2, 4))
    m.inc("requests", 3)
    m.inc("ok", 2)
    m.observe_batch(2, 2)
    m.observe_batch(4, 3)
    m.observe_latency(0.01)
    snap = m.snapshot()
    assert snap["requests"] == 3
    assert snap["ok"] == 2
    assert snap["batches"] == 2
    assert snap["batch_occupancy"]["2"]["rows"] == 2
    assert snap["batch_occupancy"]["4"]["rows"] == 3
    assert snap["batch_occupancy"]["1"]["batches"] == 0
    assert snap["latency_s"]["count"] == 1


# ------------------------------------------------------ http shutdown fix
def test_server_close_shuts_down_attached_http():
    """The serve/http satellite: Server.close() owns the HTTP teardown —
    serve loop stopped, listener socket CLOSED (fileno -1), thread
    joined — so CI smokes cannot leak the port."""
    from tpusvm.serve.http import make_http_server, start_http_thread
    from tpusvm.serve.server import Server

    srv = Server()
    httpd = make_http_server(srv, port=0)
    thread = start_http_thread(httpd)
    srv.attach_http(httpd, thread)
    assert thread.is_alive()
    srv.close()
    assert not thread.is_alive()
    assert httpd.socket.fileno() == -1  # listener really closed
    srv.close()  # idempotent


def test_stop_http_server_idempotent_after_manual_shutdown():
    from tpusvm.serve.http import (
        make_http_server,
        start_http_thread,
        stop_http_server,
    )
    from tpusvm.serve.server import Server

    srv = Server()
    httpd = make_http_server(srv, port=0)
    thread = start_http_thread(httpd)
    httpd.shutdown()
    stop_http_server(httpd, thread)
    assert not thread.is_alive()
    srv.close()


# ------------------------------------------------------------- CI pinning
def test_ci_has_conc_lint_and_stress_steps():
    """The conc gates must be wired: a conc lint sweep over every Python
    root (empty-baseline diff), conc --list-rules in the no-jax lint
    job, the self-corpus derivation from all_conc_rules(), and the
    conc-stress smoke in the test job."""
    ci = (REPO / ".github" / "workflows" / "ci.yml").read_text(
        encoding="utf-8")
    conc_lines = [ln for ln in ci.splitlines()
                  if "tpusvm.analysis conc " in ln]
    sweep = " ".join(conc_lines)
    for root in ("tpusvm/", "benchmarks/", "scripts/", "bench.py"):
        assert root in sweep, (
            f"CI conc lint sweep is missing the {root} root: {sweep!r}")
    assert "conc --list-rules" in ci
    assert "all_conc_rules" in ci
    assert 'glob("tests/analysis_corpus/conc/*.py")' in ci
    assert "conc-stress --smoke" in ci
