"""Model API tests: BinarySVC, OneVsRestSVC, persistence round-trips."""

import numpy as np
import pytest
import jax.numpy as jnp

from tpusvm.config import CascadeConfig, SVMConfig
from tpusvm.data import blobs, rings
from tpusvm.models import BinarySVC, OneVsRestSVC
from tpusvm.status import Status

CFG = SVMConfig(C=10.0, gamma=10.0)


def test_binary_svc_fit_predict():
    X, Y = rings(n=300, seed=2)
    m = BinarySVC(CFG, dtype=jnp.float64).fit(X, Y)
    assert m.status_ == Status.CONVERGED
    assert m.n_support_ > 0
    assert m.score(X, Y) > 0.97
    Xt, Yt = rings(n=100, seed=3)
    assert m.score(Xt, Yt) > 0.95
    assert m.train_time_s_ > 0


def test_binary_svc_save_load_roundtrip(tmp_path):
    X, Y = rings(n=200, seed=4)
    m = BinarySVC(CFG, dtype=jnp.float64).fit(X, Y)
    p = str(tmp_path / "model.npz")
    m.save(p)
    m2 = BinarySVC.load(p, dtype=jnp.float64)
    assert m2.config == m.config
    Xt, _ = rings(n=50, seed=5)
    np.testing.assert_allclose(
        m2.decision_function(Xt), m.decision_function(Xt), rtol=1e-10
    )
    np.testing.assert_array_equal(m2.predict(Xt), m.predict(Xt))


def test_binary_svc_cascade_matches_single_chip():
    X, Y = rings(n=512, seed=5)
    single = BinarySVC(CFG, dtype=jnp.float64).fit(X, Y)
    casc = BinarySVC(CFG, dtype=jnp.float64).fit_cascade(
        X, Y, CascadeConfig(n_shards=4, sv_capacity=256, topology="tree")
    )
    assert casc.status_ == Status.CONVERGED
    assert set(casc.sv_ids_.tolist()) == set(single.sv_ids_.tolist())
    np.testing.assert_allclose(casc.b_, single.b_, atol=1e-4)
    assert casc.cascade_rounds_ >= 2


def test_predict_unfitted_raises():
    with pytest.raises(RuntimeError, match="not fitted"):
        BinarySVC().predict(np.zeros((2, 2)))


def _four_class_data(n=400, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0, 0], [6, 0], [0, 6], [6, 6]], float)
    labels = rng.integers(0, 4, n)
    X = centers[labels] + rng.normal(0, 0.8, (n, 2))
    return X, labels.astype(np.int32)


def test_ovr_multiclass():
    X, labels = _four_class_data()
    m = OneVsRestSVC(SVMConfig(C=10.0, gamma=2.0), dtype=jnp.float64).fit(X, labels)
    assert (m.statuses_ == Status.CONVERGED).all()
    assert m.score(X, labels) > 0.97
    assert m.decision_function(X[:5]).shape == (5, 4)
    Xt, lt = _four_class_data(n=100, seed=1)
    assert m.score(Xt, lt) > 0.95


def test_ovr_batched_matches_sequential():
    X, labels = _four_class_data(n=240, seed=2)
    cfg = SVMConfig(C=10.0, gamma=2.0)
    mb = OneVsRestSVC(cfg, dtype=jnp.float64, batched=True).fit(X, labels)
    ms = OneVsRestSVC(cfg, dtype=jnp.float64, batched=False).fit(X, labels)
    # vmapped lockstep solve vs per-class sequential solve: XLA compiles
    # a DIFFERENT program for the batched (B, ...) launch than for the
    # single-head one — contraction order inside the kernel/update math
    # differs, so per-step rounding drifts, the drift steers working-set
    # selection onto a different pivot path (iteration counts land tens
    # apart), and only the CONVERGED solution agrees: statuses exact,
    # (b, coef) within the cross-engine band (measured ~1.7e-5 on b,
    # CPU f64 — the same physics as the fleet tier's documented band in
    # tests/test_fleet.py, where bitwise is a same-program property
    # only). Iteration-count equality is a same-program property too,
    # so it is NOT asserted; the per-head decisions the two engines
    # serve must still match everywhere.
    np.testing.assert_array_equal(mb.statuses_, ms.statuses_)
    np.testing.assert_allclose(mb.b_, ms.b_, atol=1e-4)
    np.testing.assert_allclose(mb.coef_, ms.coef_, atol=1e-3)
    np.testing.assert_array_equal(mb.predict(X), ms.predict(X))


def test_ovr_save_load_roundtrip(tmp_path):
    X, labels = _four_class_data(n=200, seed=3)
    m = OneVsRestSVC(SVMConfig(C=10.0, gamma=2.0), dtype=jnp.float64).fit(X, labels)
    p = str(tmp_path / "ovr.npz")
    m.save(p)
    m2 = OneVsRestSVC.load(p, dtype=jnp.float64)
    Xt, _ = _four_class_data(n=50, seed=4)
    np.testing.assert_allclose(
        m2.decision_function(Xt), m.decision_function(Xt), rtol=1e-10
    )
    np.testing.assert_array_equal(m2.predict(Xt), m.predict(Xt))


def test_save_without_suffix_roundtrips(tmp_path):
    # np.savez appends .npz; save/load must agree on the filename
    X, Y = rings(n=120, seed=7)
    m = BinarySVC(CFG, dtype=jnp.float64).fit(X, Y)
    p = str(tmp_path / "model_no_suffix")
    m.save(p)
    m2 = BinarySVC.load(p, dtype=jnp.float64)
    np.testing.assert_array_equal(m2.predict(X[:10]), m.predict(X[:10]))


def _retag_npz(src: str, dst: str, version):
    """Rewrite an npz with format_version replaced (None = dropped)."""
    with np.load(src, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != "format_version"}
    if version is not None:
        arrays["format_version"] = np.asarray(version)
    np.savez(dst, **arrays)


@pytest.mark.parametrize("kind", ["binary", "ovr"])
def test_model_format_version_roundtrip_and_rejection(tmp_path, kind):
    """Served artifacts must be forward-checkable: the saved npz carries a
    format_version, loads of the current version round-trip bitwise, and
    missing/unknown versions fail with a specific error — not a KeyError
    from whichever state field is read first."""
    from tpusvm.models.serialization import _FORMAT_VERSION, load_model

    if kind == "binary":
        X, Y = rings(n=150, seed=6)
        m = BinarySVC(CFG, dtype=jnp.float64).fit(X, Y)
        cls = BinarySVC
    else:
        X, Y = _four_class_data(n=150, seed=6)
        m = OneVsRestSVC(SVMConfig(C=10.0, gamma=2.0),
                         dtype=jnp.float64).fit(X, Y)
        cls = OneVsRestSVC
    p = str(tmp_path / "m.npz")
    m.save(p)
    with np.load(p, allow_pickle=False) as z:
        assert int(z["format_version"]) == _FORMAT_VERSION
    m2 = cls.load(p, dtype=jnp.float64)
    np.testing.assert_array_equal(
        m2.decision_function(X[:20]), m.decision_function(X[:20]))

    unknown = str(tmp_path / "unknown.npz")
    _retag_npz(p, unknown, version=_FORMAT_VERSION + 98)
    with pytest.raises(ValueError, match="unsupported model format version"):
        load_model(unknown)
    with pytest.raises(ValueError, match="unsupported model format version"):
        cls.load(unknown)

    missing = str(tmp_path / "missing.npz")
    _retag_npz(p, missing, version=None)
    with pytest.raises(ValueError, match="no format_version field"):
        load_model(missing)
    with pytest.raises(ValueError, match="no format_version field"):
        cls.load(missing)


def _rewrite_npz(src: str, dst: str, drop=(), **replace):
    """Rewrite an npz dropping keys and/or replacing values."""
    with np.load(src, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k not in drop}
    arrays.update({k: np.asarray(v) for k, v in replace.items()})
    np.savez(dst, **arrays)


def test_v1_artifact_loads_with_implicit_rbf_default(tmp_path):
    """Pre-kernel (format v1) files predate the kernel config fields:
    stripping them and retagging version 1 must load as the implicit RBF
    family with bit-identical scoring."""
    from tpusvm.models import load_any

    X, Y = rings(n=150, seed=8)
    m = BinarySVC(CFG, dtype=jnp.float64).fit(X, Y)
    p = str(tmp_path / "v2.npz")
    m.save(p)
    v1 = str(tmp_path / "v1.npz")
    _rewrite_npz(p, v1,
                 drop=("config_kernel", "config_degree", "config_coef0",
                       "config_epsilon"),
                 format_version=1)
    m2 = load_any(v1, dtype=jnp.float64)
    assert m2.config.kernel == "rbf"
    assert m2.config.degree == 3 and m2.config.coef0 == 0.0
    np.testing.assert_array_equal(
        m2.decision_function(X[:20]), m.decision_function(X[:20]))


def test_unknown_kernel_name_rejected_with_specific_error(tmp_path):
    from tpusvm.models.serialization import load_model

    X, Y = rings(n=120, seed=9)
    m = BinarySVC(CFG).fit(X, Y)
    p = str(tmp_path / "good.npz")
    m.save(p)
    bad = str(tmp_path / "bad_kernel.npz")
    _rewrite_npz(p, bad, config_kernel="laplacian")
    with pytest.raises(ValueError, match="kernel family 'laplacian'"):
        load_model(bad)
    with pytest.raises(ValueError, match="kernel family 'laplacian'"):
        BinarySVC.load(bad)


def test_kernel_config_roundtrips_through_npz(tmp_path):
    from tpusvm.data import blobs

    X, Y = blobs(n=150, d=4, seed=10)
    cfg = SVMConfig(C=1.0, gamma=0.5, kernel="poly", degree=2, coef0=1.5)
    m = BinarySVC(cfg, dtype=jnp.float64).fit(X, Y)
    p = str(tmp_path / "poly.npz")
    m.save(p)
    m2 = BinarySVC.load(p, dtype=jnp.float64)
    assert m2.config.kernel == "poly"
    assert m2.config.degree == 2
    assert m2.config.coef0 == 1.5
    np.testing.assert_array_equal(
        m2.decision_function(X[:20]), m.decision_function(X[:20]))


def test_model_task_sniff(tmp_path):
    from tpusvm.data import svr_sine
    from tpusvm.models import EpsilonSVR
    from tpusvm.models.serialization import model_task

    X, Y = rings(n=120, seed=11)
    BinarySVC(CFG).fit(X, Y).save(str(tmp_path / "svc.npz"))
    assert model_task(str(tmp_path / "svc.npz")) == "svc"

    Xm, Ym = _four_class_data(n=120, seed=11)
    OneVsRestSVC(SVMConfig(C=10.0, gamma=2.0)).fit(Xm, Ym).save(
        str(tmp_path / "ovr.npz"))
    assert model_task(str(tmp_path / "ovr.npz")) == "ovr"

    Xr, tr = svr_sine(n=120, d=1, seed=11)
    EpsilonSVR(SVMConfig(C=10.0, gamma=20.0)).fit(Xr, tr).save(
        str(tmp_path / "svr.npz"))
    assert model_task(str(tmp_path / "svr.npz")) == "svr"


def test_binary_svc_linear_and_poly_fit_predict(tmp_path):
    from tpusvm.data import blobs

    X, Y = blobs(n=200, d=5, seed=12)
    for cfg in (SVMConfig(C=1.0, kernel="linear"),
                SVMConfig(C=1.0, gamma=1.0, kernel="poly", degree=2,
                          coef0=1.0)):
        m = BinarySVC(cfg).fit(X, Y)
        assert m.status_.name == "CONVERGED"
        assert m.score(X, Y) > 0.95
        p = str(tmp_path / f"{cfg.kernel}.npz")
        m.save(p)
        m2 = BinarySVC.load(p)
        np.testing.assert_array_equal(m2.predict(X), m.predict(X))


def test_fit_warns_on_non_convergence():
    import warnings as w
    X, Y = rings(n=200, seed=8)
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        m = BinarySVC(SVMConfig(C=10.0, gamma=10.0, max_iter=3),
                      dtype=jnp.float64, solver="pair").fit(X, Y)
    assert m.status_ == Status.MAX_ITER
    assert any("MAX_ITER" in str(r.message) for r in rec)


def test_ovr_blocked_solver_matches_pair():
    X, labels = _four_class_data(n=240, seed=2)
    cfg = SVMConfig(C=10.0, gamma=2.0)
    # f64 accumulators: the mixed-precision mode (pure-f32 blocked solves
    # can stall near convergence, which BinarySVC/OneVsRestSVC surface as a
    # RuntimeWarning with exactly this suggestion)
    mp = OneVsRestSVC(cfg, dtype=jnp.float32, solver="pair",
                      accum_dtype=jnp.float64).fit(X, labels)
    mb = OneVsRestSVC(cfg, dtype=jnp.float32, solver="blocked",
                      accum_dtype=jnp.float64).fit(X, labels)
    assert (mb.statuses_ == Status.CONVERGED).all()
    # different trajectories, same optimum (solution-level parity)
    np.testing.assert_allclose(mb.b_, mp.b_, atol=2e-3)
    assert mb.score(X, labels) > 0.97


def test_ovr_rejects_bad_solver():
    with pytest.raises(ValueError, match="solver must be"):
        OneVsRestSVC(solver="cuda")


def test_ovr_solver_opts_forwarded():
    X, labels = _four_class_data(n=240, seed=2)
    cfg = SVMConfig(C=10.0, gamma=2.0)
    m = OneVsRestSVC(cfg, dtype=jnp.float32, solver="blocked",
                     accum_dtype=jnp.float64,
                     solver_opts={"q": 64, "max_inner": 128}).fit(X, labels)
    assert (m.statuses_ == Status.CONVERGED).all()
    assert m.score(X, labels) > 0.97
    # a bogus knob must raise from the solve call, proving forwarding
    with pytest.raises(TypeError):
        OneVsRestSVC(cfg, solver="blocked",
                     solver_opts={"bogus": 1}).fit(X, labels)


def test_ovr_class_parallel_matches_single_device():
    """class_parallel=True (BASELINE config 5: the class axis sharded over
    the device mesh) reaches the same solution as the single-device vmap —
    4 classes over the test mesh's devices, padded with dummy all-negative
    classes that terminate immediately. Parity is solution-level (same SV
    union / b / predictions): shard_map compiles the same math into a
    different schedule, so fp-tie trajectories may differ microscopically,
    exactly like the repo's cross-engine parity standard."""
    import jax

    from tpusvm.parallel.mesh import make_mesh

    X, labels = _four_class_data(n=240, seed=5)
    cfg = SVMConfig(C=10.0, gamma=2.0)
    m0 = OneVsRestSVC(cfg, dtype=jnp.float64, batched=True).fit(X, labels)
    # an explicit 3-device mesh for K=4 classes forces pad=2: the dummy
    # all-negative padding branch (what 10 classes on 8 chips hits) must
    # actually execute — the default mesh would size itself to min(K, 8)
    # = 4 devices and never pad
    mesh = make_mesh(3, devices=jax.devices()[:3], axis="classes")
    mp = OneVsRestSVC(cfg, dtype=jnp.float64, class_parallel=True,
                      mesh=mesh).fit(X, labels)
    assert (mp.statuses_ == Status.CONVERGED).all()
    assert mp.coef_.shape[0] == 4  # dummy padding classes were dropped
    # b is only determined to the 2*tau stopping window (tau=1e-5);
    # measured cross-schedule agreement is ~6e-6
    np.testing.assert_allclose(mp.b_, m0.b_, atol=5e-5)
    assert m0.X_sv_.shape == mp.X_sv_.shape  # same SV union
    Xt, lt = _four_class_data(n=100, seed=6)
    np.testing.assert_array_equal(mp.predict(Xt), m0.predict(Xt))
    assert mp.score(Xt, lt) > 0.95


def test_ovr_class_parallel_rejects_blocked_solver():
    import pytest

    with pytest.raises(ValueError, match="class_parallel"):
        OneVsRestSVC(SVMConfig(), solver="blocked", class_parallel=True)


def test_binary_svc_mesh_sharded_predict_matches_single_device():
    """decision_function/predict/score with a mesh shard the test rows
    over the 8-device CPU mesh; scores must match the single-device path
    (no collectives in the forward pass — each row is independent).
    m=100 deliberately does not divide 8 (uneven final shard). Score
    agreement is to ~ULP (the partitioned matmul may tile the
    contraction differently); predicted labels could in principle flip
    on an exactly-zero margin, which is measure-zero on real data."""
    import jax

    from tpusvm.data import rings
    from tpusvm.parallel.mesh import make_mesh

    X, Y = rings(n=300, seed=7)
    m = BinarySVC(SVMConfig(C=10.0, gamma=10.0), dtype=jnp.float64).fit(X, Y)
    Xt, Yt = rings(n=100, seed=8)
    mesh = make_mesh(len(jax.devices()))
    s0 = m.decision_function(Xt)
    s1 = m.decision_function(Xt, mesh=mesh)
    np.testing.assert_allclose(s1, s0, rtol=1e-12, atol=1e-12)
    np.testing.assert_array_equal(m.predict(Xt, mesh=mesh), m.predict(Xt))
    assert m.score(Xt, Yt, mesh=mesh) == m.score(Xt, Yt)


def test_ovr_mesh_sharded_predict_matches_single_device():
    import jax

    from tpusvm.parallel.mesh import make_mesh

    X, labels = _four_class_data(n=240, seed=9)
    m = OneVsRestSVC(SVMConfig(C=10.0, gamma=2.0), dtype=jnp.float64).fit(
        X, labels)
    Xt, lt = _four_class_data(n=100, seed=10)  # 100 % 8 != 0
    mesh = make_mesh(len(jax.devices()))
    np.testing.assert_allclose(
        m.decision_function(Xt, mesh=mesh), m.decision_function(Xt),
        rtol=1e-12, atol=1e-12)
    np.testing.assert_array_equal(m.predict(Xt, mesh=mesh), m.predict(Xt))
    assert m.score(Xt, lt, mesh=mesh) == m.score(Xt, lt)


def _mesh_2d():
    import jax
    from jax.sharding import Mesh

    # fixed 2-device slice so the guard test runs under any device count
    return Mesh(np.asarray(jax.devices()[:2]).reshape(1, 2), ("a", "b"))


def test_mesh_sharded_predict_rejects_multi_axis_mesh():
    """shard_rows_padded pads by mesh.devices.size but shards only axis 0,
    so a multi-axis mesh must be rejected up front (ADVICE r3) instead of
    producing an obscure sharding error or silent over-padding."""
    import pytest

    from tpusvm.data import rings
    from tpusvm.parallel.mesh import shard_rows_padded

    with pytest.raises(ValueError, match="1-D mesh"):
        shard_rows_padded(_mesh_2d(), jnp.zeros((16, 3)))
    X, Y = rings(n=64, seed=7)
    m = BinarySVC(SVMConfig(C=10.0, gamma=10.0)).fit(X, Y)
    with pytest.raises(ValueError, match="1-D mesh"):
        m.decision_function(X, mesh=_mesh_2d())


def test_ovr_class_parallel_rejects_multi_axis_mesh():
    import pytest

    X, labels = _four_class_data(n=64, seed=5)
    m = OneVsRestSVC(SVMConfig(), class_parallel=True, mesh=_mesh_2d())
    with pytest.raises(ValueError, match="1-D mesh"):
        m.fit(X, labels)


def test_mesh_sharded_predict_compiles_with_zero_collectives():
    """The sharded-serving contract is STRUCTURAL, not just numerical: the
    compiled HLO for both estimators' mesh paths must contain no
    collectives (all-gather/collective-permute/all-reduce of the test
    rows would mean every device gets every row and per-device
    memory/compute does not shrink). The binary path uses the FLAT matmul
    for exactly this reason — the blocked scan variant's reshape destroys
    row sharding and XLA all-gathers (caught by review in round 3)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpusvm.parallel.mesh import make_mesh
    from tpusvm.solver.predict import decision_function_flat

    mesh = make_mesh(8)
    rows = NamedSharding(mesh, P("cascade"))
    Xq = jnp.zeros((1024, 16), jnp.float32)
    Xsv = jnp.zeros((64, 16), jnp.float32)

    lowered = jax.jit(
        lambda Xq, Xsv, coef, b: decision_function_flat(
            Xq, Xsv, coef, b, gamma=0.5),
        in_shardings=(rows, None, None, None),
    ).lower(Xq, Xsv, jnp.zeros(64, jnp.float32), jnp.float32(0.0))
    hlo = lowered.compile().as_text()
    for coll in ("all-gather", "collective-permute", "all-reduce",
                 "all-to-all"):
        assert coll not in hlo, f"{coll} in sharded binary predict HLO"

    from tpusvm.models.ovr import _ovr_scores

    lowered = jax.jit(
        lambda Xq, Xsv, coef, b: _ovr_scores(Xq, Xsv, coef, b, 0.5),
        in_shardings=(rows, None, None, None),
    ).lower(Xq, Xsv, jnp.zeros((4, 64), jnp.float32),
            jnp.zeros(4, jnp.float32))
    hlo = lowered.compile().as_text()
    for coll in ("all-gather", "collective-permute", "all-reduce",
                 "all-to-all"):
        assert coll not in hlo, f"{coll} in sharded OVR predict HLO"
