"""Blocked decision_function edge coverage: bucket-padded serving safety.

The serving subsystem (tpusvm.serve) pads coalesced batches to power-of-two
row buckets and promises scores BIT-IDENTICAL to a direct decision_function
call on the same rows. That promise rests on per-row independence of the
blocked evaluator: each test row's score is its own K-row dot product, so
neither the scan blocking (m % block != 0, block > m, block == m) nor
zero-row padding may perturb any real row's bits. These tests pin that down
against the unblocked single-matmul evaluation (decision_function_flat).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.solver.predict import decision_function, decision_function_flat


def _problem(m=100, n=256, d=16, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    X_test = jnp.asarray(rng.random((m, d)), dtype)
    X_train = jnp.asarray(rng.random((n, d)), dtype)
    coef = jnp.asarray(rng.normal(size=n), dtype)
    b = jnp.asarray(0.25, dtype)
    return X_test, X_train, coef, b


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
@pytest.mark.parametrize("block", [
    32,    # m % block != 0 (100 = 3*32 + 4: padded final block)
    7,     # m % block != 0 with a non-power-of-two block
    256,   # block > m (whole set in one padded block)
    100,   # block == m (exact fit, no padding)
])
def test_blocked_decisions_bit_identical_to_flat(block, dtype):
    m = 100
    X_test, X_train, coef, b = _problem(m=m, dtype=dtype)
    flat = np.asarray(decision_function_flat(
        X_test, X_train, coef, b, gamma=0.5))
    blocked = np.asarray(decision_function(
        X_test, X_train, coef, b, gamma=0.5, block=block))
    assert blocked.shape == (m,)
    np.testing.assert_array_equal(blocked, flat)


@pytest.mark.parametrize("m", [1, 2, 3, 8, 33])
def test_decisions_independent_of_batch_size(m):
    """A row's score must not depend on how many rows ride the batch —
    THE property that makes bucket-padded serving exact: serving computes
    a (bucket, n) program over padded rows, a direct call computes
    (m, n), and the real rows must agree bitwise either way."""
    X_test, X_train, coef, b = _problem(m=64)
    full = np.asarray(decision_function(
        X_test, X_train, coef, b, gamma=0.5))
    prefix = np.asarray(decision_function(
        X_test[:m], X_train, coef, b, gamma=0.5))
    np.testing.assert_array_equal(prefix, full[:m])
    # zero-row padding, the serve bucket layout: real rows first, zero
    # rows after — slicing the reals must recover the unpadded scores
    Xp = jnp.concatenate([X_test[:m], jnp.zeros_like(X_test[: 8 - m % 8])])
    padded = np.asarray(decision_function(
        Xp, X_train, coef, b, gamma=0.5))
    np.testing.assert_array_equal(padded[:m], full[:m])


def test_single_row_matches_full_evaluation():
    """The m=1 bucket (a lone request on an idle server) is the extreme
    padding case: one real row in a block-sized program."""
    X_test, X_train, coef, b = _problem(m=16)
    full = np.asarray(decision_function_flat(
        X_test, X_train, coef, b, gamma=0.5))
    for i in range(4):
        one = np.asarray(decision_function(
            X_test[i:i + 1], X_train, coef, b, gamma=0.5))
        np.testing.assert_array_equal(one, full[i:i + 1])
