"""Blocked working-set solver vs. oracle: solution-level parity.

The blocked solver intentionally follows a different iteration trajectory
(many updates per X pass); the reference's own parity criterion — identical
SV set, b within tolerance, same stopping rule satisfied — is what must
hold (SURVEY.md §4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, blobs, rings
from tpusvm.oracle import get_sv_indices, smo_train
from tpusvm.solver import blocked_smo_solve
from tpusvm.status import Status


def _data(gen, **kw):
    X, Y = gen(**kw)
    return MinMaxScaler().fit_transform(X), Y


@pytest.mark.parametrize(
    "gen,kw,cfg,q",
    [
        (rings, dict(n=512, seed=5), SVMConfig(C=10.0, gamma=10.0), 64),
        (rings, dict(n=512, seed=5), SVMConfig(C=10.0, gamma=10.0), 1024),
        (blobs, dict(n=151, d=5, seed=7), SVMConfig(C=1.0, gamma=0.125), 32),
    ],
)
def test_blocked_matches_oracle(gen, kw, cfg, q):
    Xs, Y = _data(gen, **kw)
    o = smo_train(Xs, Y, cfg)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y),
        C=cfg.C, gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau, q=q,
    )
    assert int(r.status) == Status.CONVERGED
    # the reference's stopping rule must actually be satisfied
    assert float(r.b_low) <= float(r.b_high) + 2 * cfg.tau
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(r.alpha)), get_sv_indices(o.alpha)
    )
    np.testing.assert_allclose(float(r.b), o.b, atol=1e-4)
    np.testing.assert_allclose(np.asarray(r.alpha), o.alpha, atol=1e-3)


def test_blocked_padding_invariance():
    Xs, Y = _data(blobs, n=100, seed=3)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y), C=1.0, gamma=0.125, q=32,
    )
    pad = 28
    Xp = np.concatenate([Xs, np.zeros((pad, Xs.shape[1]))])
    Yp = np.concatenate([Y, np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(100, bool), np.zeros(pad, bool)])
    rp = blocked_smo_solve(
        jnp.asarray(Xp), jnp.asarray(Yp), valid=jnp.asarray(valid),
        C=1.0, gamma=0.125, q=32,
    )
    assert (np.asarray(rp.alpha)[100:] == 0).all()
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(rp.alpha)[:100]),
        get_sv_indices(np.asarray(r.alpha)),
    )
    np.testing.assert_allclose(float(rp.b), float(r.b), atol=1e-6)


def test_blocked_warm_start():
    Xs, Y = _data(blobs, n=90, seed=9)
    r = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), C=1.0, gamma=0.125, q=32)
    r2 = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y), alpha0=r.alpha,
        C=1.0, gamma=0.125, q=32, warm_start=True,
    )
    assert int(r2.status) == Status.CONVERGED
    assert int(r2.n_iter) == 1  # converged at the first global check
    np.testing.assert_allclose(np.asarray(r2.alpha), np.asarray(r.alpha))


def test_blocked_single_class_no_working_set():
    Xs, Y = _data(blobs, n=64, seed=1)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.ones(64, jnp.int32), C=1.0, gamma=0.5, q=16,
    )
    assert int(r.status) == Status.NO_WORKING_SET
    assert (np.asarray(r.alpha) == 0).all()


def test_blocked_respects_max_iter():
    Xs, Y = _data(rings, n=512, seed=5)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y), C=10.0, gamma=10.0,
        max_iter=10, q=16, max_inner=4,
    )
    assert int(r.status) == Status.MAX_ITER
    # checked between outer rounds: overshoot bounded by max_inner
    assert int(r.n_iter) - 1 < 10 + 4


def test_blocked_surfaces_nonpos_eta():
    # duplicate points with opposite labels: eta == 0 on the first pair —
    # must report NONPOS_ETA like the pairwise solver, not generic STALLED
    Xd = np.zeros((4, 2))
    Yd = np.array([1, -1, 1, -1], np.int32)
    r = blocked_smo_solve(jnp.asarray(Xd), jnp.asarray(Yd), C=1.0, gamma=0.5, q=4)
    assert int(r.status) == Status.NONPOS_ETA
