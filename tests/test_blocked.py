"""Blocked working-set solver vs. oracle: solution-level parity.

The blocked solver intentionally follows a different iteration trajectory
(many updates per X pass); the reference's own parity criterion — identical
SV set, b within tolerance, same stopping rule satisfied — is what must
hold (SURVEY.md §4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, blobs, rings
from tpusvm.oracle import get_sv_indices, smo_train
from tpusvm.solver import blocked_smo_solve
from tpusvm.status import Status


def _data(gen, **kw):
    X, Y = gen(**kw)
    return MinMaxScaler().fit_transform(X), Y


@pytest.mark.parametrize(
    "gen,kw,cfg,q",
    [
        (rings, dict(n=512, seed=5), SVMConfig(C=10.0, gamma=10.0), 64),
        (rings, dict(n=512, seed=5), SVMConfig(C=10.0, gamma=10.0), 1024),
        (blobs, dict(n=151, d=5, seed=7), SVMConfig(C=1.0, gamma=0.125), 32),
    ],
)
def test_blocked_matches_oracle(gen, kw, cfg, q):
    Xs, Y = _data(gen, **kw)
    o = smo_train(Xs, Y, cfg)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y),
        C=cfg.C, gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau, q=q,
    )
    assert int(r.status) == Status.CONVERGED
    # the reference's stopping rule must actually be satisfied
    assert float(r.b_low) <= float(r.b_high) + 2 * cfg.tau
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(r.alpha)), get_sv_indices(o.alpha)
    )
    np.testing.assert_allclose(float(r.b), o.b, atol=1e-4)
    np.testing.assert_allclose(np.asarray(r.alpha), o.alpha, atol=1e-3)


def test_blocked_padding_invariance():
    Xs, Y = _data(blobs, n=100, seed=3)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y), C=1.0, gamma=0.125, q=32,
    )
    pad = 28
    Xp = np.concatenate([Xs, np.zeros((pad, Xs.shape[1]))])
    Yp = np.concatenate([Y, np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(100, bool), np.zeros(pad, bool)])
    rp = blocked_smo_solve(
        jnp.asarray(Xp), jnp.asarray(Yp), valid=jnp.asarray(valid),
        C=1.0, gamma=0.125, q=32,
    )
    assert (np.asarray(rp.alpha)[100:] == 0).all()
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(rp.alpha)[:100]),
        get_sv_indices(np.asarray(r.alpha)),
    )
    np.testing.assert_allclose(float(rp.b), float(r.b), atol=1e-6)


def test_blocked_warm_start():
    Xs, Y = _data(blobs, n=90, seed=9)
    r = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), C=1.0, gamma=0.125, q=32)
    r2 = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y), alpha0=r.alpha,
        C=1.0, gamma=0.125, q=32, warm_start=True,
    )
    assert int(r2.status) == Status.CONVERGED
    assert int(r2.n_iter) == 1  # converged at the first global check
    np.testing.assert_allclose(np.asarray(r2.alpha), np.asarray(r.alpha))


def test_blocked_warm_start_equivalence_from_neighbour_solution():
    """The tune subsystem's contract on the solver surface, tested
    directly (previously only covered indirectly via cascade tests):
    warm-starting from a NEIGHBOURING hyperparameter point's solution
    must (a) converge, (b) reproduce the cold solve's SV IDs and b within
    tolerance — same optimum, different trajectory — and (c) cost
    markedly fewer updates than the cold solve; and re-solving from the
    point's OWN solution must terminate in a handful of outer rounds."""
    from tpusvm.oracle import get_sv_indices

    Xs, Y = _data(rings, n=256, noise=0.3, seed=11)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    kw = dict(C=4.0, gamma=4.0, q=64)
    cold = blocked_smo_solve(Xj, Yj, **kw)
    assert int(cold.status) == Status.CONVERGED

    # (a)-(c): seed from the adjacent grid point gamma*sqrt(2)
    donor = blocked_smo_solve(Xj, Yj, C=4.0, gamma=4.0 * 2 ** 0.5, q=64)
    assert int(donor.status) == Status.CONVERGED
    warm = blocked_smo_solve(Xj, Yj, alpha0=donor.alpha,
                             warm_start=True, **kw)
    assert int(warm.status) == Status.CONVERGED
    assert float(warm.b_low) <= float(warm.b_high) + 2 * 1e-5
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(warm.alpha)),
        get_sv_indices(np.asarray(cold.alpha)),
    )
    np.testing.assert_allclose(float(warm.b), float(cold.b), atol=1e-4)
    assert int(warm.n_iter) < int(cold.n_iter)

    # own-solution resume: converges at (or within a handful of rounds
    # of) the first global stop check
    resume = blocked_smo_solve(Xj, Yj, alpha0=cold.alpha,
                               warm_start=True, **kw)
    assert int(resume.status) == Status.CONVERGED
    assert int(resume.n_outer) <= 3
    np.testing.assert_allclose(np.asarray(resume.alpha),
                               np.asarray(cold.alpha), atol=1e-9)


def test_blocked_precomputed_sn_identical():
    # the tune driver's fold-cache path: passing cached sq_norms must be
    # numerically invisible (same trajectory, same result)
    from tpusvm.ops.rbf import sq_norms

    Xs, Y = _data(blobs, n=120, d=4, seed=2)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    a = blocked_smo_solve(Xj, Yj, C=1.0, gamma=0.25, q=32)
    b = blocked_smo_solve(Xj, Yj, sn=sq_norms(Xj), C=1.0, gamma=0.25, q=32)
    assert int(a.n_iter) == int(b.n_iter)
    np.testing.assert_array_equal(np.asarray(a.alpha), np.asarray(b.alpha))
    assert float(a.b) == float(b.b)


def test_pad_alpha0_resume_shapes():
    from tpusvm.solver.blocked import pad_alpha0

    a = np.array([1.0, 2.0, 3.0])
    np.testing.assert_array_equal(pad_alpha0(a, 5), [1, 2, 3, 0, 0])
    np.testing.assert_array_equal(pad_alpha0(a, 2), [1, 2])
    assert pad_alpha0(a, 3) is a
    aj = jnp.asarray(a)
    out = pad_alpha0(aj, 5)
    assert isinstance(out, jnp.ndarray) and out.shape == (5,)
    np.testing.assert_array_equal(np.asarray(out), [1, 2, 3, 0, 0])


def test_blocked_single_class_no_working_set():
    Xs, Y = _data(blobs, n=64, seed=1)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.ones(64, jnp.int32), C=1.0, gamma=0.5, q=16,
    )
    assert int(r.status) == Status.NO_WORKING_SET
    assert (np.asarray(r.alpha) == 0).all()


def test_blocked_respects_max_iter():
    Xs, Y = _data(rings, n=512, seed=5)
    r = blocked_smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y), C=10.0, gamma=10.0,
        max_iter=10, q=16, max_inner=4,
    )
    assert int(r.status) == Status.MAX_ITER
    # checked between outer rounds: overshoot bounded by max_inner
    assert int(r.n_iter) - 1 < 10 + 4


def test_blocked_surfaces_nonpos_eta():
    # duplicate points with opposite labels: eta == 0 on the first pair —
    # must report NONPOS_ETA like the pairwise solver, not generic STALLED
    Xd = np.zeros((4, 2))
    Yd = np.array([1, -1, 1, -1], np.int32)
    r = blocked_smo_solve(jnp.asarray(Xd), jnp.asarray(Yd), C=1.0, gamma=0.5, q=4)
    assert int(r.status) == Status.NONPOS_ETA


def test_blocked_refine_drift_control():
    """refine mode re-validates convergence on a reconstructed f and still
    terminates (bounded by max_refines), landing at the same solution."""
    rng = np.random.default_rng(7)
    n, d = 512, 16
    X = jnp.asarray(rng.random((n, d)), jnp.float32)
    Y = jnp.asarray(np.where(rng.random(n) < 0.5, 1, -1), jnp.int32)
    kw = dict(C=10.0, gamma=1.0, tau=1e-5, q=128, max_inner=256,
              max_outer=2000, accum_dtype=jnp.float64)
    r0 = blocked_smo_solve(X, Y, **kw)
    r1 = blocked_smo_solve(X, Y, refine=n, max_refines=2, **kw)
    assert int(r0.status) == Status.CONVERGED
    assert int(r1.status) == Status.CONVERGED
    # the refine path actually fired (at least one f reconstruction ran)
    assert r0.n_refines is None or int(r0.n_refines) == 0
    assert int(r1.n_refines) >= 1
    # same optimum within the f32 kernel-evaluation noise band
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-3)
    np.testing.assert_allclose(np.asarray(r1.alpha), np.asarray(r0.alpha),
                               atol=5e-3)


def test_blocked_refine_overcap_skips_reconstruction():
    """More live alphas than the cap: reconstruction must be skipped, not
    computed from a truncated coefficient set (which would corrupt f and
    derail the solve to a different b)."""
    rng = np.random.default_rng(7)
    n, d = 512, 16
    # random labels on uniform points -> nearly every alpha ends up at a
    # bound, so live alphas far exceed a cap of 128
    X = jnp.asarray(rng.random((n, d)), jnp.float32)
    Y = jnp.asarray(np.where(rng.random(n) < 0.5, 1, -1), jnp.int32)
    kw = dict(C=10.0, gamma=1.0, tau=1e-5, q=128, max_inner=256,
              max_outer=2000, accum_dtype=jnp.float64)
    r0 = blocked_smo_solve(X, Y, **kw)
    r1 = blocked_smo_solve(X, Y, refine=128, max_refines=2, **kw)
    assert int(np.asarray(jnp.sum(r1.alpha > 0))) > 128
    assert int(r1.status) == Status.CONVERGED
    assert int(r1.n_refines) == 0  # over-cap: reconstruction never ran
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-6)
    np.testing.assert_allclose(np.asarray(r1.alpha), np.asarray(r0.alpha),
                               atol=1e-6)


def test_blocked_fast_matmul_requires_refine():
    X = jnp.zeros((8, 2), jnp.float32)
    Y = jnp.asarray([1, -1] * 4, jnp.int32)
    with pytest.raises(ValueError, match="refine"):
        blocked_smo_solve(X, Y, matmul_precision="default")
    with pytest.raises(ValueError, match="matmul_precision"):
        blocked_smo_solve(X, Y, matmul_precision="bf16")


def test_blocked_fast_matmul_matches_baseline():
    """matmul_precision='default' + refine lands at the same optimum as the
    full-precision run (on CPU the knob is a no-op numerically — true f32
    either way — so this pins the plumbing and the refine pairing; the
    bf16-vs-f32 trajectory itself is exercised on TPU by bench/probes)."""
    Xs, Y = _data(rings, n=512, seed=5)
    kw = dict(C=10.0, gamma=10.0, tau=1e-5, q=128, max_inner=256,
              max_outer=2000, accum_dtype=jnp.float64)
    r0 = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), **kw)
    r1 = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y),
                           matmul_precision="default", refine=512,
                           max_refines=2, **kw)
    assert int(r0.status) == Status.CONVERGED
    assert int(r1.status) == Status.CONVERGED
    sv0 = set(np.flatnonzero(np.asarray(r0.alpha) > 1e-8).tolist())
    sv1 = set(np.flatnonzero(np.asarray(r1.alpha) > 1e-8).tolist())
    assert sv0 == sv1
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-3)


def test_blocked_rejects_bad_wss():
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="wss must be"):
        blocked_smo_solve(X, Y, inner="xla", wss=7)


def test_blocked_wss2_xla_same_optimum_fewer_updates():
    """The XLA engine's second-order partner selection (round 4: same
    maximal-gain math as the pallas kernel) reaches the same optimum as
    first-order, in fewer or equal updates — the whole point of wss=2."""
    Xs, Y = _data(rings, n=512, seed=5)
    kw = dict(C=10.0, gamma=10.0, tau=1e-5, q=64, max_inner=256,
              inner="xla", accum_dtype=jnp.float64)
    r1 = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), **kw, wss=1)
    r2 = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), **kw, wss=2)
    assert int(r1.status) == Status.CONVERGED
    assert int(r2.status) == Status.CONVERGED
    assert int(r2.n_iter) <= int(r1.n_iter)
    sv1 = set(np.flatnonzero(np.asarray(r1.alpha) > 1e-8))
    sv2 = set(np.flatnonzero(np.asarray(r2.alpha) > 1e-8))
    # different trajectories stop anywhere inside the 2*tau band: allow
    # tau-level boundary flips, same standard as the cross-engine tests
    assert len(sv1 ^ sv2) <= max(2, len(sv1) // 25)
    np.testing.assert_allclose(float(r2.b), float(r1.b), atol=1e-3)


def test_blocked_wss2_survives_degenerate_partner_candidates():
    """Regression (parity-fuzz seed 4047): rings with near-coincident
    points used to kill the XLA wss=2 engine mid-solve — the gain
    formula's clamped denominator made a near-duplicate of x[i_h] the
    argmax partner, and the analytic update bails on eta <= eps
    (NONPOS_ETA, b off by 0.22 while every other engine converged).
    Degenerate partners are now excluded from the gain selection, so the
    exact failing instance must converge to the same solution as wss=1
    under BOTH selection modes."""
    from benchmarks.common import random_instance

    rng = np.random.default_rng(4047)
    _, _, X, Y, C, gamma = random_instance(
        rng, 4047, (96, 640), (2, 24), [1.0, 10.0, 100.0],
        [0.125, 0.5, 2.0, 10.0])
    Xs = MinMaxScaler().fit_transform(X)
    kw = dict(C=C, gamma=gamma, tau=1e-5, q=256, max_inner=1024,
              inner="xla", accum_dtype=jnp.float64)
    r1 = blocked_smo_solve(jnp.asarray(Xs, jnp.float32), jnp.asarray(Y),
                           **kw, wss=1)
    assert int(r1.status) == Status.CONVERGED
    sv1 = set(np.flatnonzero(np.asarray(r1.alpha) > 1e-8))
    for selection in ("exact", "approx"):
        r2 = blocked_smo_solve(jnp.asarray(Xs, jnp.float32),
                               jnp.asarray(Y), **kw, wss=2,
                               selection=selection)
        assert int(r2.status) == Status.CONVERGED, selection
        sv2 = set(np.flatnonzero(np.asarray(r2.alpha) > 1e-8))
        assert len(sv1 ^ sv2) <= max(2, len(sv1) // 25), selection
        np.testing.assert_allclose(float(r2.b), float(r1.b), atol=1e-3)


def test_blocked_wss2_xla_matches_pallas_interpret_trajectory():
    """Both engines implement the SAME wss=2 selection rule on
    non-degenerate data: on identical subproblem inputs (random floats —
    no eta<=eps partner ever wins the gain argmax here) the XLA loop and
    the (interpreted) pallas kernel must produce the same alpha
    trajectory to f32 resolution. On DEGENERATE data the engines
    deliberately diverge in trajectory (XLA excludes dead partners from
    selection, pallas selects-then-shrinks) while reaching the same
    optimum — see _inner_smo's docstring and
    test_blocked_wss2_survives_degenerate_partner_candidates."""
    from tpusvm.ops.pallas.inner_smo import inner_smo_pallas
    from tpusvm.solver.blocked import _inner_smo
    from tpusvm.ops.rbf import rbf_cross

    rng = np.random.default_rng(3)
    qq = 128
    Xb = jnp.asarray(rng.random((qq, 6)), jnp.float32)
    y = jnp.asarray(np.where(rng.random(qq) < 0.5, 1, -1), jnp.int32)
    K = rbf_cross(Xb, Xb, 1.5)
    a0 = jnp.zeros(qq, jnp.float32)
    f0 = -y.astype(jnp.float32)
    act = jnp.ones(qq, bool)
    a_x = np.asarray(_inner_smo(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                                64, wss=2)[0])
    a_p = np.asarray(inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12,
                                      1e-5, max_inner=64, interpret=True,
                                      wss=2)[0])
    np.testing.assert_allclose(a_p, a_x, atol=1e-3)


def test_blocked_selection_approx_same_optimum():
    """Approximate working-set selection (lax.approx_min_k/max_k) converges
    to the same optimum as exact top_k: selection only chooses WHICH
    violators each round optimises, while the stopping decision stays on
    exact global reductions."""
    Xs, Y = _data(rings, n=512, seed=5)
    kw = dict(C=10.0, gamma=10.0, tau=1e-5, q=64, max_inner=256,
              accum_dtype=jnp.float64)
    r_e = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), **kw,
                            selection="exact")
    r_a = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), **kw,
                            selection="approx")
    assert int(r_e.status) == Status.CONVERGED
    assert int(r_a.status) == Status.CONVERGED
    sv_e = set(np.flatnonzero(np.asarray(r_e.alpha) > 1e-8))
    sv_a = set(np.flatnonzero(np.asarray(r_a.alpha) > 1e-8))
    # on CPU approx_min_k/max_k reduce exactly, so the trajectories (and SV
    # sets) coincide; on TPU the approx path genuinely differs and both runs
    # stop anywhere inside the 2*tau band, so allow tau-level boundary flips
    assert len(sv_e ^ sv_a) <= max(2, len(sv_e) // 50)
    np.testing.assert_allclose(float(r_a.b), float(r_e.b), atol=1e-3)


def test_blocked_rejects_bad_selection():
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="selection must be"):
        blocked_smo_solve(X, Y, selection="topk")


def test_blocked_fused_fupdate_same_optimum():
    """The fused Pallas f-update (interpret off-TPU) reaches the same
    optimum as the XLA contraction path."""
    Xs, Y = _data(rings, n=256, seed=5)
    kw = dict(C=10.0, gamma=10.0, tau=1e-5, q=64, max_inner=128,
              accum_dtype=jnp.float64, selection="exact")
    r0 = blocked_smo_solve(jnp.asarray(Xs, jnp.float32), jnp.asarray(Y), **kw)
    r1 = blocked_smo_solve(jnp.asarray(Xs, jnp.float32), jnp.asarray(Y), **kw,
                           fused_fupdate=True)
    assert int(r0.status) == Status.CONVERGED
    assert int(r1.status) == Status.CONVERGED
    sv0 = set(np.flatnonzero(np.asarray(r0.alpha) > 1e-8))
    sv1 = set(np.flatnonzero(np.asarray(r1.alpha) > 1e-8))
    assert len(sv0 ^ sv1) <= max(2, len(sv0) // 25)
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-3)


def test_blocked_fused_fupdate_rejects_reduced_precision():
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="fused_fupdate"):
        blocked_smo_solve(X, Y, fused_fupdate=True,
                          matmul_precision="default", refine=16)


def test_resolve_solver_config_matches_solver_behavior():
    """The shared resolution helper (what benchmarks record per-row) must
    mirror the solver's actual rules: q clamps to even n, inner='auto' is
    XLA off-TPU, selection='auto' is exact off-TPU, and wss passes
    through unchanged now that BOTH engines implement second-order
    selection (round 4; the ADVICE-r2 degradation rule is gone)."""
    from tpusvm.solver.blocked import resolve_solver_config

    # q clamp: odd n drops to n-1; tiny n floors at 2
    assert resolve_solver_config(385, 1024)[0] == 384
    assert resolve_solver_config(384, 128)[0] == 128
    assert resolve_solver_config(1, 128)[0] == 2
    # this suite runs on CPU: auto resolves to (xla, exact); wss survives
    q, inner, wss, selection = resolve_solver_config(
        60000, 2048, inner="auto", wss=2, selection="auto")
    assert (q, inner, wss, selection) == (2048, "xla", 2, "exact")
    # explicit engine/selection pass through; wss=2 survives on pallas
    _, inner, wss, _ = resolve_solver_config(
        60000, 2048, inner="pallas", wss=2, selection="approx")
    assert (inner, wss) == ("pallas", 2)


def test_resolve_fused_fupdate_rules():
    """round-4 adoption: fused_fupdate='auto' must resolve OFF on a CPU
    backend (the kernel would interpret), pass explicit bools through,
    reject junk, and gate on the kernel's VMEM feasibility model."""
    from tpusvm.ops.pallas.fused_fupdate import fused_feasible
    from tpusvm.solver.blocked import resolve_fused_fupdate

    # this suite runs on CPU: auto is always off here
    assert resolve_fused_fupdate(60000, 784, q=2048) is False
    # explicit requests pass through regardless of backend
    assert resolve_fused_fupdate(60000, 784, q=2048, fused=True) is True
    assert resolve_fused_fupdate(60000, 784, q=2048, fused=False) is False
    with pytest.raises(ValueError, match="fused_fupdate must be"):
        resolve_fused_fupdate(60000, 784, q=2048, fused="yes")
    # a truthy int must not sneak past as True (1 == True but 1 is not
    # True, and the solver's bf16 rejection checks `is True`)
    with pytest.raises(ValueError, match="fused_fupdate must be"):
        resolve_fused_fupdate(60000, 784, q=2048, fused=1)
    # the feasibility model the TPU-side auto gate consults: the bench
    # shape fits; a huge resident XB^T block (q*d over the ~64 MB budget)
    # or a tall-skinny stack-busting d does not
    assert fused_feasible(2048, 784, 60000) is True
    assert fused_feasible(8192, 8192) is False       # resident blowup
    # stack branch in isolation: resident fits (7.7 MB) but the 128-row
    # floor block's per-step stack (15.49 MB — dominated by the
    # (block, d) X input block at this wide d) busts the 15 MB budget
    assert fused_feasible(64, 30000) is False
    assert fused_feasible(128, 1_000_000, 8) is False  # both budgets blown
