"""IDX/npz ingestion tests for scripts/make_mnist_csv.py.

The reference expects mnist3_{train,test}_data.csv in cwd and ships no
converter (SURVEY.md §4); scripts/make_mnist_csv.py is the replacement.
This environment has no real MNIST (zero egress), so these tests hand-build
tiny IDX files — the exact byte layout of the official distribution
(big-endian magic 2051/2049 headers, uint8 payload), both raw and .gz — and
drive the converter end-to-end into CSVs read back by the framework's own
reader. Whoever finally has real MNIST on disk gets a first-try-correct
pipeline.
"""

import gzip
import os
import struct
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from scripts.make_mnist_csv import (  # noqa: E402
    load_idx,
    load_npz,
    main,
    read_idx_images,
    read_idx_labels,
)

# 3 "images" of 2x2 pixels + labels, deterministic
IMAGES = np.array(
    [[[0, 255], [7, 13]], [[1, 2], [3, 4]], [[9, 8], [7, 6]]], np.uint8
)
LABELS = np.array([1, 0, 7], np.uint8)


def _idx_images_bytes(imgs: np.ndarray) -> bytes:
    n, rows, cols = imgs.shape
    return struct.pack(">IIII", 2051, n, rows, cols) + imgs.tobytes()


def _idx_labels_bytes(labels: np.ndarray) -> bytes:
    return struct.pack(">II", 2049, len(labels)) + labels.tobytes()


def _write_idx_dir(dir_, gz=False):
    names = {
        "train-images-idx3-ubyte": _idx_images_bytes(IMAGES),
        "train-labels-idx1-ubyte": _idx_labels_bytes(LABELS),
        "t10k-images-idx3-ubyte": _idx_images_bytes(IMAGES[:2]),
        "t10k-labels-idx1-ubyte": _idx_labels_bytes(LABELS[:2]),
    }
    for name, payload in names.items():
        if gz:
            with gzip.open(os.path.join(dir_, name + ".gz"), "wb") as f:
                f.write(payload)
        else:
            with open(os.path.join(dir_, name), "wb") as f:
                f.write(payload)


@pytest.mark.parametrize("gz", [False, True], ids=["raw", "gzip"])
def test_load_idx_roundtrip(tmp_path, gz):
    _write_idx_dir(tmp_path, gz=gz)
    xtr, ytr, xte, yte = load_idx(str(tmp_path))
    np.testing.assert_array_equal(xtr, IMAGES.reshape(3, 4))
    np.testing.assert_array_equal(ytr, LABELS)
    np.testing.assert_array_equal(xte, IMAGES[:2].reshape(2, 4))
    np.testing.assert_array_equal(yte, LABELS[:2])


def test_read_idx_rejects_bad_magic(tmp_path):
    img = tmp_path / "train-images-idx3-ubyte"
    img.write_bytes(struct.pack(">IIII", 2049, 1, 2, 2) + b"\0" * 4)
    with pytest.raises(ValueError, match="magic"):
        read_idx_images(str(img))
    lab = tmp_path / "train-labels-idx1-ubyte"
    lab.write_bytes(struct.pack(">II", 2051, 1) + b"\0")
    with pytest.raises(ValueError, match="magic"):
        read_idx_labels(str(lab))


def test_load_idx_missing_file_message(tmp_path):
    with pytest.raises(FileNotFoundError, match="train-images"):
        load_idx(str(tmp_path))


def test_load_npz_keras_layout(tmp_path):
    path = tmp_path / "mnist.npz"
    np.savez(
        path,
        x_train=IMAGES,
        y_train=LABELS,
        x_test=IMAGES[:2],
        y_test=LABELS[:2],
    )
    xtr, ytr, xte, yte = load_npz(str(path))
    assert xtr.shape == (3, 4) and xte.shape == (2, 4)
    np.testing.assert_array_equal(ytr, LABELS)


@pytest.mark.parametrize("gz", [False, True], ids=["raw", "gzip"])
def test_main_idx_to_csv_read_back_by_framework(tmp_path, gz):
    """Full pipeline: IDX bytes -> reference-layout CSVs -> framework CSV
    reader with the reference's '1 vs rest' label mapping (!=1 -> -1)."""
    from tpusvm.data.csv_reader import read_csv

    idx_dir = tmp_path / "idx"
    out_dir = tmp_path / "csv"
    idx_dir.mkdir()
    _write_idx_dir(idx_dir, gz=gz)
    assert main(["--idx", str(idx_dir), "--out-dir", str(out_dir)]) == 0

    X, Y = read_csv(str(out_dir / "mnist3_train_data.csv"))
    np.testing.assert_array_equal(X, IMAGES.reshape(3, 4).astype(np.float64))
    np.testing.assert_array_equal(Y, [1, -1, -1])  # labels 1,0,7 -> 1,-1,-1
    Xt, Yt = read_csv(str(out_dir / "mnist3_test_data.csv"))
    assert Xt.shape == (2, 4)
    np.testing.assert_array_equal(Yt, [1, -1])
