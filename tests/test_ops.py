"""RBF op tests: dot-form vs direct-form numerical agreement."""

import jax.numpy as jnp
import numpy as np

from tpusvm.ops import rbf_cross, rbf_matvec, rbf_row, rbf_rows_at, rbf_rows_at_direct


def test_rows_dot_matches_direct():
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.random((300, 17)))
    idx = jnp.asarray([5, 123], jnp.int32)
    dot = rbf_rows_at(X, idx, 0.5)
    direct = rbf_rows_at_direct(X, idx, 0.5)
    np.testing.assert_allclose(np.asarray(dot), np.asarray(direct), atol=1e-12)
    # and both match the single-row reference op
    np.testing.assert_allclose(
        np.asarray(dot[0]), np.asarray(rbf_row(X, X[5], 0.5)), atol=1e-12
    )


def test_rbf_matvec_matches_dense():
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.random((257, 9)))  # non-multiple of block
    coef = jnp.asarray(rng.standard_normal(257))
    got = rbf_matvec(X, coef, 0.25, block=64)
    K = rbf_cross(X, X, 0.25)
    np.testing.assert_allclose(np.asarray(got), np.asarray(K @ coef), atol=1e-10)


def test_rbf_cross_matvec_matches_dense():
    """The blocked f-update contraction vs the dense kernel matvec, across
    block geometries including the clamped-overlapping-tail reassembly
    (n % block != 0), single-block (n <= block) and exact-fit cases."""
    from tpusvm.ops import rbf_cross_matvec

    rng = np.random.default_rng(2)

    for n, block in [(257, 64), (256, 64), (63, 64), (64, 64), (1, 8),
                     (130, 64)]:
        X = jnp.asarray(rng.random((n, 9)), jnp.float32)
        XB = jnp.asarray(rng.random((16, 9)), jnp.float32)
        coef = jnp.asarray(rng.standard_normal(16), jnp.float32)
        got = rbf_cross_matvec(X, XB, coef, 0.25, block=block)
        want = rbf_cross(X, XB, 0.25) @ coef
        assert got.shape == (n,)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5,
            err_msg=f"n={n} block={block}",
        )
