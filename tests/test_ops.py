"""RBF op tests: dot-form vs direct-form numerical agreement."""

import jax.numpy as jnp
import numpy as np

from tpusvm.ops import rbf_cross, rbf_matvec, rbf_row, rbf_rows_at, rbf_rows_at_direct


def test_rows_dot_matches_direct():
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.random((300, 17)))
    idx = jnp.asarray([5, 123], jnp.int32)
    dot = rbf_rows_at(X, idx, 0.5)
    direct = rbf_rows_at_direct(X, idx, 0.5)
    np.testing.assert_allclose(np.asarray(dot), np.asarray(direct), atol=1e-12)
    # and both match the single-row reference op
    np.testing.assert_allclose(
        np.asarray(dot[0]), np.asarray(rbf_row(X, X[5], 0.5)), atol=1e-12
    )


def test_rbf_matvec_matches_dense():
    rng = np.random.default_rng(1)
    X = jnp.asarray(rng.random((257, 9)))  # non-multiple of block
    coef = jnp.asarray(rng.standard_normal(257))
    got = rbf_matvec(X, coef, 0.25, block=64)
    K = rbf_cross(X, X, 0.25)
    np.testing.assert_allclose(np.asarray(got), np.asarray(K @ coef), atol=1e-10)
