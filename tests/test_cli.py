"""CLI + utils tests (SURVEY.md §5.1, §5.5, §5.6, C26)."""

import json

import numpy as np
import pytest

from tpusvm.cli import main
from tpusvm.utils import PhaseTimer, RunLogger, trace


# ------------------------------------------------------------------- utils
def test_phase_timer_accumulates():
    t = PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b"):
        pass
    assert t["a"] >= 0 and "b" in t
    t.add("b", 1.0)
    assert t["b"] >= 1.0
    rep = t.report()
    assert "a time:" in rep and "elapsed time:" in rep
    assert set(t.asdict()) == {"a", "b", "total"}


def test_run_logger_jsonl(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    with RunLogger(jsonl_path=path) as log:
        log.info("n = %d", 5)
        log.round_header(2)
        log.event("round", round=2, sv=np.int64(7), arr=np.arange(2))
    out = capsys.readouterr().out
    assert "n = 5" in out and "=== Round 2 ===" in out
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["event"] == "round" and rec["sv"] == 7 and rec["arr"] == [0, 1]


def test_run_logger_non_primary_silent(tmp_path, capsys):
    log = RunLogger(jsonl_path=str(tmp_path / "x.jsonl"), primary=False)
    log.info("should not print")
    log.event("e")
    log.close()
    assert capsys.readouterr().out == ""
    assert not (tmp_path / "x.jsonl").exists()


def test_trace_noop():
    with trace(None):
        pass


# --------------------------------------------------------------------- cli
def test_cli_train_single_and_predict(tmp_path, capsys):
    model = str(tmp_path / "m.npz")
    rc = main([
        "train", "--synthetic", "rings", "--n", "200", "--n-test", "60",
        "--C", "10", "--gamma", "10", "--save", model,
        "--jsonl", str(tmp_path / "run.jsonl"),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "n = 200, n_features = 2" in out
    assert "iterations = " in out and "b = " in out
    assert "SV count = " in out and "accuracy = " in out
    assert "training time:" in out and "prediction time:" in out
    events = [json.loads(l) for l in open(tmp_path / "run.jsonl")]
    assert {e["event"] for e in events} >= {"data", "train", "eval", "timing"}

    from tpusvm.data import rings, write_csv

    X, Y = rings(n=80, seed=3)
    csv = str(tmp_path / "t.csv")
    write_csv(csv, X, Y)
    rc = main(["predict", "--model", model, "--data", csv])
    assert rc == 0
    assert "accuracy = " in capsys.readouterr().out

    # mesh-sharded serving through the CLI: same accuracy as plain predict
    rc = main(["predict", "--model", model, "--data", csv,
               "--mesh-predict"])
    assert rc == 0
    assert "accuracy = " in capsys.readouterr().out


def test_cli_predict_multiclass_model_autodetected(tmp_path, capsys):
    """`predict` must work on a --multiclass-saved model: the state is
    auto-detected (classes key), CSV labels stay RAW instead of the
    binary != 1 -> -1 mapping, and --scores prints one column per
    class."""
    import numpy as np

    from tpusvm.data import write_csv
    from tpusvm.data.synthetic import mnist_like_multiclass

    model = str(tmp_path / "ovr.npz")
    # one clean multiclass dataset split into train/test CSVs so the
    # saved model and the predict data share a distribution
    X, labels = mnist_like_multiclass(n=256, d=16, seed=9)
    train_csv = str(tmp_path / "mc_train.csv")
    csv = str(tmp_path / "mc.csv")
    write_csv(train_csv, X[:192], labels[:192])
    write_csv(csv, X[192:], labels[192:])
    rc = main([
        "train", "--train", train_csv, "--multiclass",
        "--gamma", "0.0625", "--save", model, "--quiet",
    ])
    assert rc == 0
    capsys.readouterr()
    rc = main(["predict", "--model", model, "--data", csv])
    assert rc == 0
    out = capsys.readouterr().out
    assert "accuracy = " in out
    acc = float(out.split("accuracy = ")[1].split()[0])
    assert acc > 0.5  # raw labels compared against argmax classes

    rc = main(["predict", "--model", model, "--data", csv, "--scores",
               "--mesh-predict"])
    assert rc == 0
    rows = capsys.readouterr().out.strip().splitlines()
    assert len(rows) == 64
    assert len(rows[0].split()) == 10  # one score column per class (K=10)
    assert np.isfinite([float(v) for v in rows[0].split()]).all()


def test_cli_solver_opt_passthrough(capsys):
    # KEY=VALUE knobs reach the blocked solver: q=64 on a 200-point problem
    # still converges; a bogus knob must fail loudly (TypeError from the
    # solve call), proving the values are actually forwarded
    rc = main([
        "train", "--synthetic", "rings", "--n", "200", "--n-test", "60",
        "--C", "10", "--gamma", "10",
        "--solver-opt", "q=64", "--solver-opt", "max_inner=128",
        "--solver-opt", "warm_start=false",
    ])
    assert rc == 0
    assert "accuracy = " in capsys.readouterr().out

    from tpusvm.cli import _parse_solver_opts  # value typing, in isolation

    assert _parse_solver_opts([
        "q=64", "warm_start=false", "refine=1e4", "matmul_precision=default",
    ]) == {"q": 64, "warm_start": False, "refine": 10000.0,
           "matmul_precision": "default"}

    # unknown knobs fail BEFORE the data load, with the valid names listed
    with pytest.raises(SystemExit, match="bogus_knob"):
        main([
            "train", "--synthetic", "rings", "--n", "100", "--n-test", "30",
            "--C", "10", "--gamma", "10", "--solver-opt", "bogus_knob=1",
        ])

    # hyperparameters with dedicated flags are rejected (would collide
    # with the explicit fit kwargs)
    with pytest.raises(SystemExit, match="dedicated flags"):
        main([
            "train", "--synthetic", "rings", "--n", "100", "--n-test", "30",
            "--solver-opt", "gamma=10",
        ])

    with pytest.raises(SystemExit, match="KEY=VALUE"):
        main([
            "train", "--synthetic", "rings", "--n", "100", "--n-test", "30",
            "--solver-opt", "novalue",
        ])

    # the oracle has no static solver knobs; silent no-op would mislead
    with pytest.raises(SystemExit, match="oracle"):
        main([
            "train", "--synthetic", "rings", "--n", "100", "--n-test", "30",
            "--mode", "oracle", "--solver-opt", "q=64",
        ])


def test_cli_train_oracle(capsys):
    rc = main([
        "train", "--synthetic", "rings", "--n", "120", "--n-test", "40",
        "--mode", "oracle", "--C", "10", "--gamma", "10",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "(b_high - b_low)/2 * 1e10" in out


def test_cli_train_cascade(capsys):
    rc = main([
        "train", "--synthetic", "rings", "--n", "160", "--n-test", "40",
        "--mode", "cascade", "--topology", "star", "--shards", "4",
        "--sv-capacity", "128", "--C", "10", "--gamma", "10",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "=== Round 1 ===" in out and "cascade:" in out


def test_cli_rejects_ambiguous_source():
    with pytest.raises(SystemExit):
        main(["train"])
    with pytest.raises(SystemExit):
        main(["train", "--train", "x.csv", "--synthetic", "rings"])


def test_cli_info(capsys):
    assert main(["info"]) == 0
    assert "backend:" in capsys.readouterr().out


def test_cli_n_limit_caps_synthetic(capsys):
    rc = main([
        "train", "--synthetic", "rings", "--n", "200", "--n-test", "40",
        "--n-limit", "100", "--C", "10", "--gamma", "10",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "n = 100," in out
    # the cap must not leak the cut training rows into the test set
    assert "/40)" in out


def test_cli_distributed_flag_plumbs_through(capsys, monkeypatch):
    """--distributed must call jax.distributed.initialize (the MPI_Init
    equivalent) before command dispatch, passing explicit geometry."""
    import jax

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    rc = main([
        "--distributed", "--coordinator-address", "127.0.0.1:8476",
        "--num-processes", "1", "--process-id", "0", "info",
    ])
    assert rc == 0
    assert calls == [{
        "coordinator_address": "127.0.0.1:8476",
        "num_processes": 1,
        "process_id": 0,
    }]
    # flags accepted after the subcommand too (launcher scripts append
    # user flags there), with TPU-pod auto-detection (no explicit geometry)
    calls.clear()
    assert main(["info", "--distributed"]) == 0
    assert calls == [{}]
    capsys.readouterr()


def test_cli_not_distributed_by_default(capsys, monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: calls.append(kw)
    )
    assert main(["info"]) == 0
    assert calls == []
    capsys.readouterr()


def test_cli_class_parallel_multiclass(capsys):
    """--multiclass --class-parallel trains the one-vs-rest classes
    sharded over the (virtual 8-device) mesh through the CLI."""
    rc = main([
        "train", "--synthetic", "blobs", "--n", "160", "--n-test", "0",
        "--d", "4", "--gamma", "0.25", "--C", "1.0",
        "--multiclass", "--class-parallel",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "classes = " in out


def test_cli_class_parallel_requires_multiclass(capsys):
    with pytest.raises(SystemExit, match="requires --multiclass"):
        main(["train", "--synthetic", "blobs", "--n", "64",
              "--class-parallel"])


def test_cli_stratify_requires_cascade(capsys):
    with pytest.raises(SystemExit, match="--mode cascade"):
        main(["train", "--synthetic", "rings", "--n", "64", "--stratify"])


def test_cli_class_parallel_rejects_blocked(capsys):
    with pytest.raises(SystemExit, match="pair solver"):
        main(["train", "--synthetic", "blobs", "--n", "64", "--multiclass",
              "--class-parallel", "--solver", "blocked"])


def test_cli_class_parallel_allows_distributed(monkeypatch):
    # round 4: class-parallel is multi-host capable (the class axis shards
    # over the global mesh), so --distributed + --class-parallel is a
    # VALID combination — the old single-controller rejection must be
    # gone. Stub initialize (single process here) and verify the run
    # reaches it and completes.
    import jax

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    rc = main(["--distributed", "train", "--synthetic", "blobs", "--n",
               "64", "--n-test", "0", "--d", "4", "--gamma", "0.25",
               "--multiclass", "--class-parallel", "--quiet"])
    assert rc == 0
    assert calls  # the MPI_Init equivalent ran
    # the REAL 2-process execution of this path lives in
    # tests/test_distributed.py::test_two_process_class_parallel_multiclass


# ------------------------------------------------------- kernel/task matrix
def test_cli_train_kernel_smoke_cells(capsys):
    """The CI kernel-matrix smoke cells: linear SVC and rbf SVR, each
    with its own workload and gate (blobs/accuracy, sine/R^2)."""
    rc = main(["train", "--kernel", "linear", "--smoke", "-q"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "train smoke ok [linear/svc]" in out

    rc = main(["train", "--task", "svr", "--smoke", "-q"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "train smoke ok [rbf/svr]" in out


def test_cli_svr_train_predict_info(tmp_path, capsys):
    model = str(tmp_path / "svr.npz")
    rc = main(["train", "--task", "svr", "--synthetic", "sine", "--d", "2",
               "--n", "200", "--n-test", "50", "--gamma", "20",
               "--save", model])
    assert rc == 0
    out = capsys.readouterr().out
    assert "r2 = " in out and "rmse = " in out

    rc = main(["info", model])
    assert rc == 0
    out = capsys.readouterr().out
    assert "model: epsilon-SVR" in out and "epsilon=0.1" in out

    # regression CSV: continuous last column round-trips through predict
    from tpusvm.data import svr_sine

    X, t = svr_sine(n=60, d=2, seed=5)
    csv = str(tmp_path / "t.csv")
    with open(csv, "w") as fh:
        fh.write("a,b,target\n")
        for row, ti in zip(X, t):
            fh.write(",".join(repr(float(v)) for v in row)
                     + f",{float(ti)!r}\n")
    rc = main(["predict", "--model", model, "--data", csv])
    assert rc == 0
    assert "r2 = " in capsys.readouterr().out


def test_cli_calibrate_and_proba(tmp_path, capsys):
    from tpusvm.data import rings, write_csv

    model = str(tmp_path / "cal.npz")
    rc = main(["train", "--synthetic", "rings", "--n", "200",
               "--C", "10", "--gamma", "10", "--calibrate", "2",
               "--save", model, "-q"])
    assert rc == 0
    capsys.readouterr()
    rc = main(["info", model])
    assert "calibrated: yes" in capsys.readouterr().out and rc == 0

    X, Y = rings(n=20, seed=9)
    csv = str(tmp_path / "t.csv")
    write_csv(csv, X, Y)
    rc = main(["predict", "--model", model, "--data", csv, "--proba"])
    assert rc == 0
    probs = [float(l) for l in capsys.readouterr().out.splitlines()]
    assert len(probs) == 20 and all(0.0 <= p <= 1.0 for p in probs)

    # an uncalibrated model refuses --proba with a clear message
    plain = str(tmp_path / "plain.npz")
    rc = main(["train", "--synthetic", "rings", "--n", "150", "--C", "10",
               "--gamma", "10", "--save", plain, "-q"])
    assert rc == 0
    capsys.readouterr()
    with pytest.raises(SystemExit, match="no Platt coefficients"):
        main(["predict", "--model", plain, "--data", csv, "--proba"])


def test_cli_kernel_task_flag_validation(capsys):
    with pytest.raises(SystemExit, match="--task svr requires --mode"):
        main(["train", "--task", "svr", "--synthetic", "sine",
              "--mode", "cascade"])
    with pytest.raises(SystemExit, match="requires --task svr"):
        main(["train", "--synthetic", "sine", "--n", "50"])
    with pytest.raises(SystemExit, match="requires --task svc"):
        main(["train", "--task", "svr", "--synthetic", "sine",
              "--calibrate", "2"])
    with pytest.raises(SystemExit, match="--calibrate needs"):
        main(["train", "--synthetic", "rings", "--calibrate", "1"])
