"""EpsilonSVR: the doubled-variable regression task end to end.

Covers the estimator surface (fit/predict/score/save-load), oracle parity
of the doubled solve, the epsilon-tube property (residuals of interior
SVs sit at the tube boundary), and the twin-pair degeneracy argument
(identical doubled rows can never be selected as a violating pair, so
the solve terminates CONVERGED, not NONPOS_ETA).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, svr_sine
from tpusvm.kernels.svr import collapse_duals
from tpusvm.models import EpsilonSVR, load_any
from tpusvm.oracle import svr_train
from tpusvm.status import Status

jax.config.update("jax_enable_x64", True)


def _cfg(**kw):
    base = dict(C=10.0, gamma=20.0, epsilon=0.1)
    base.update(kw)
    return SVMConfig(**base)


def _fit(n=240, seed=0, solver="blocked", **cfg_kw):
    X, t = svr_sine(n=n, d=1, noise=0.05, seed=seed)
    model = EpsilonSVR(config=_cfg(**cfg_kw), solver=solver)
    model.fit(X, t)
    return model, X, t


def test_svr_fits_sine():
    model, X, t = _fit()
    assert model.status_ == Status.CONVERGED
    assert model.score(X, t) > 0.9
    # held-out
    Xh, th = svr_sine(n=100, d=1, noise=0.05, seed=99)
    assert model.score(Xh, th) > 0.85


def test_svr_oracle_parity():
    X, t = svr_sine(n=200, d=1, noise=0.05, seed=3)
    Xs = MinMaxScaler().fit_transform(X)
    cfg = _cfg()
    o = svr_train(Xs, t, cfg)
    assert o.status == Status.CONVERGED
    coef_o = collapse_duals(o.alpha)
    sv_o = set(np.nonzero(np.abs(coef_o) > cfg.sv_tol)[0].tolist())

    model = EpsilonSVR(config=cfg, scale=False)
    model.fit(Xs, t)
    sv_m = set(model.sv_ids_.tolist())
    assert len(sv_m ^ sv_o) <= max(2, len(sv_o) // 25)
    assert abs(model.b_ - o.b) < 2.5e-2


def test_svr_pair_solver_matches_blocked():
    m_blk, X, t = _fit(seed=5)
    m_pair, _, _ = _fit(seed=5, solver="pair")
    assert m_pair.status_ == Status.CONVERGED
    sym = set(m_blk.sv_ids_.tolist()) ^ set(m_pair.sv_ids_.tolist())
    assert len(sym) <= max(2, len(m_blk.sv_ids_) // 10)
    np.testing.assert_allclose(m_pair.predict(X), m_blk.predict(X),
                               atol=5e-2)


def test_svr_epsilon_tube_property():
    # interior SVs (0 < |coef| < C) sit ON the tube: |t - y(x)| ~ epsilon
    model, X, t = _fit()
    cfg = model.config
    pred = model.predict(X)
    coef = model.sv_coef_
    interior = (np.abs(coef) > 1e-6) & (np.abs(coef) < cfg.C - 1e-6)
    if interior.any():
        resid = np.abs(t[model.sv_ids_[interior]]
                       - pred[model.sv_ids_[interior]])
        np.testing.assert_allclose(resid, cfg.epsilon, atol=2e-2)
    # non-SV rows are strictly inside the tube (up to solver tolerance)
    non_sv = np.setdiff1d(np.arange(len(t)), model.sv_ids_)
    assert np.all(np.abs(t[non_sv] - pred[non_sv])
                  <= cfg.epsilon + 1e-2)


def test_svr_duplicate_rows_do_not_stall():
    # the doubling makes every row appear twice with opposite labels and
    # eta = 0 between the twins; the selection argument (kernels/svr.py)
    # says that pair is never violating — the solve must end CONVERGED
    X, t = svr_sine(n=120, d=1, noise=0.0, seed=7)
    model = EpsilonSVR(config=_cfg())
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)  # no non-conv warn
        model.fit(X, t)
    assert model.status_ == Status.CONVERGED


def test_svr_save_load_roundtrip(tmp_path):
    model, X, t = _fit()
    p = str(tmp_path / "svr.npz")
    model.save(p)
    loaded = load_any(p)
    assert isinstance(loaded, EpsilonSVR)
    assert loaded.config.epsilon == model.config.epsilon
    np.testing.assert_array_equal(loaded.sv_coef_, model.sv_coef_)
    np.testing.assert_allclose(loaded.predict(X), model.predict(X),
                               atol=0)  # bit-identical scoring path


def test_svr_load_rejects_classifier_artifact(tmp_path):
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC

    X, Y = rings(n=120, seed=0)
    clf = BinarySVC(config=SVMConfig(C=10.0, gamma=10.0)).fit(X, Y)
    p = str(tmp_path / "clf.npz")
    clf.save(p)
    with pytest.raises(ValueError, match="not an EpsilonSVR"):
        EpsilonSVR.load(p)
    # but load_any dispatches correctly
    assert isinstance(load_any(p), BinarySVC)


def test_svr_unfitted_raises():
    with pytest.raises(RuntimeError, match="not fitted"):
        EpsilonSVR().predict(np.zeros((2, 2)))


def test_svr_linear_kernel():
    # linear SVR on a linear target: near-perfect fit, tiny SV set
    rng = np.random.default_rng(0)
    X = rng.uniform(-1, 1, (200, 3))
    t = X @ np.asarray([1.0, -2.0, 0.5]) + 0.3
    model = EpsilonSVR(config=SVMConfig(C=10.0, kernel="linear",
                                        epsilon=0.05))
    model.fit(X, t)
    assert model.status_ == Status.CONVERGED
    assert model.score(X, t) > 0.99


def test_svr_solver_opts_and_telemetry():
    X, t = svr_sine(n=150, d=1, noise=0.05, seed=1)
    model = EpsilonSVR(config=_cfg(), solver_opts={"telemetry": 16})
    model.fit(X, t)
    assert model.convergence_ is not None
    assert model.convergence_["rounds_recorded"] >= 1
