"""Opt-in real-MNIST parity harness — THE parity run.

The reference's correctness contract (README.md:35-38, report §6): every
implementation trained on real MNIST-60k (one-vs-rest digit "1", C=10,
gamma=0.00125) reaches test accuracy 0.9969 (9969/10000) with exactly 1548
support vectors and b ~ -5.9026 (cross-implementation agreement < 0.003%).

This environment is zero-egress, so real MNIST cannot be fetched; the test
therefore SKIPS unless TPUSVM_MNIST_DIR points at a directory containing
mnist3_train_data.csv / mnist3_test_data.csv (produce them from the
official IDX files with scripts/make_mnist_csv.py --idx). With real files
supplied, this asserts the parity constants:

  TPUSVM_MNIST_DIR=/path/to/csvs python -m pytest tests/test_mnist_parity.py -v
"""

import os

import pytest

DIR = os.environ.get("TPUSVM_MNIST_DIR")
TRAIN = os.path.join(DIR, "mnist3_train_data.csv") if DIR else None
TEST = os.path.join(DIR, "mnist3_test_data.csv") if DIR else None

pytestmark = pytest.mark.skipif(
    not (DIR and os.path.exists(TRAIN) and os.path.exists(TEST)),
    reason="set TPUSVM_MNIST_DIR to a directory with mnist3_{train,test}"
    "_data.csv (real MNIST) to run the reference-parity assertion",
)

# reference constants (README.md:35-38; report §6)
REF_ACCURACY = 0.9969
REF_N_SV = 1548
REF_B = -5.9026206
REF_B_RTOL = 3e-5  # "< 0.003%" cross-implementation agreement


def test_real_mnist_parity_constants():
    from tpusvm.data.native_io import read_csv_fast
    from tpusvm.models import BinarySVC

    X, Y = read_csv_fast(TRAIN, binary_labels=True)
    Xt, Yt = read_csv_fast(TEST, binary_labels=True)
    assert X.shape == (60000, 784), "expected real MNIST-60k train CSV"
    assert Xt.shape == (10000, 784), "expected real MNIST-10k test CSV"

    model = BinarySVC().fit(X, Y)  # zero-config = the parity configuration

    acc = model.score(Xt, Yt)
    assert round(acc, 4) == REF_ACCURACY, (
        f"accuracy {acc:.4f} != reference {REF_ACCURACY}"
    )
    assert model.n_support_ == REF_N_SV, (
        f"SV count {model.n_support_} != reference {REF_N_SV}"
    )
    assert abs(model.b_ - REF_B) <= abs(REF_B) * REF_B_RTOL, (
        f"b {model.b_:.7f} not within 0.003% of reference {REF_B}"
    )
