"""Pallas kernel tests (interpret mode — runs on the CPU test mesh)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.ops.pallas.inner_smo import inner_smo_pallas
from tpusvm.ops.rbf import rbf_cross
from tpusvm.solver.blocked import _inner_smo, blocked_smo_solve
from tpusvm.status import Status


def _subproblem(q=128, seed=0, d=8, gamma=0.5):
    rng = np.random.default_rng(seed)
    X = rng.random((q, d)).astype(np.float32)
    y = np.where(rng.random(q) < 0.5, 1, -1).astype(np.int32)
    K = rbf_cross(jnp.asarray(X), jnp.asarray(X), gamma)
    a0 = jnp.zeros(q, jnp.float32)
    f0 = -jnp.asarray(y, jnp.float32)
    act = jnp.ones(q, bool)
    return K, jnp.asarray(y), a0, f0, act


def test_inner_smo_pallas_invariants():
    """Box feasibility, sum(y*a) conservation, dual ascent."""
    K, y, a0, f0, act = _subproblem()
    C = 10.0
    a, n_upd, progress, reason = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=512, interpret=True
    )
    a = np.asarray(a)
    assert int(n_upd) > 0 and bool(progress)
    assert (a >= -1e-6).all() and (a <= C + 1e-6).all()
    # every 2-variable update preserves sum(y*a); started at 0
    np.testing.assert_allclose(float(np.sum(a * np.asarray(y))), 0.0, atol=1e-3)
    # dual objective W(a) = sum(a) - 0.5 a^T Q a must have increased from 0
    Q = np.asarray(K) * np.outer(np.asarray(y), np.asarray(y))
    dual = a.sum() - 0.5 * a @ Q @ a
    assert dual > 0.1
    assert int(reason) in (
        Status.CONVERGED, Status.NO_WORKING_SET, Status.MAX_ITER
    )


@pytest.mark.parametrize("q", [128, 256])
def test_inner_smo_pallas_matches_xla_before_bailout(q):
    """With no numerical bail-outs, the f32 trajectories are identical.

    q=128 is the degenerate single-row layout (R=1); q=256 exercises the
    multi-row (R, 128) sublane-packed layout, whose row-major index
    mapping must preserve the (1, q) first-occurrence tie-breaks."""
    K, y, a0, f0, act = _subproblem(q=q, seed=3)
    a_x, n_x, _, r_x = _inner_smo(K, y, a0, f0, act, 10.0, 1e-12, 1e-5, 200)
    a_p, n_p, _, r_p = inner_smo_pallas(
        K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=200, interpret=True
    )
    # the XLA engine hit its cap cleanly (no stall/infeasible/eta bail-out),
    # so shrinking never engaged and the two runs are the same sequence
    assert int(r_x) == Status.MAX_ITER, Status(int(r_x)).name
    assert int(n_x) == int(n_p) == 200
    np.testing.assert_array_equal(np.asarray(a_x), np.asarray(a_p))


def test_inner_smo_pallas_rejects_unaligned():
    K, y, a0, f0, act = _subproblem(q=100)
    with pytest.raises(ValueError, match="q % 128"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True)


def test_blocked_pallas_engine_matches_xla_solution():
    """Same optimum (solution-level parity) from both inner engines."""
    rng = np.random.default_rng(42)
    n, d = 256, 16
    X = jnp.asarray(rng.random((n, d)), jnp.float32)
    Y = jnp.asarray(np.where(rng.random(n) < 0.5, 1, -1), jnp.int32)
    kw = dict(C=10.0, gamma=1.0, tau=1e-5, q=128, max_inner=256,
              max_outer=500, accum_dtype=jnp.float64)
    r_x = blocked_smo_solve(X, Y, inner="xla", **kw)
    r_p = blocked_smo_solve(X, Y, inner="pallas", **kw)
    assert int(r_x.status) == Status.CONVERGED
    assert int(r_p.status) == Status.CONVERGED
    np.testing.assert_allclose(float(r_p.b), float(r_x.b), atol=5e-4)
    sv_x = np.asarray(r_x.alpha) > 1e-8
    sv_p = np.asarray(r_p.alpha) > 1e-8
    # SV sets agree up to tau-level boundary cases
    assert (sv_x != sv_p).mean() < 0.02
    np.testing.assert_allclose(
        np.asarray(r_p.alpha), np.asarray(r_x.alpha), atol=2e-3
    )


def test_blocked_rejects_bad_inner():
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="inner must be"):
        blocked_smo_solve(X, Y, inner="cuda")
    with pytest.raises(ValueError, match="multiple of 128"):
        blocked_smo_solve(X, Y, inner="pallas", q=16)


def test_inner_smo_wss2_same_optimum():
    """Second-order partner selection reaches the same subproblem optimum
    as first-order (different trajectory), in fewer or equal updates."""
    K, y, a0, f0, act = _subproblem(q=128, seed=5)
    C = 10.0
    a1, n1, _, _ = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=4096, interpret=True,
        wss=1)
    a2, n2, _, _ = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=4096, interpret=True,
        wss=2)
    Q = np.asarray(K) * np.outer(np.asarray(y), np.asarray(y))

    def dual(a):
        a = np.asarray(a)
        return a.sum() - 0.5 * a @ Q @ a

    assert int(n2) <= int(n1)
    # wss1 can end slightly short of the optimum when f32 shrinking
    # deactivates stalled violators; wss2 must be at least as good and
    # within the same tau-limited band
    assert dual(a2) >= dual(a1) - 1e-3
    np.testing.assert_allclose(dual(a2), dual(a1), rtol=1e-3)
    # sum(y*a) conservation holds for the second-order trajectory too
    np.testing.assert_allclose(float(np.sum(np.asarray(a2) * np.asarray(y))),
                               0.0, atol=1e-3)


def test_inner_smo_rejects_bad_wss():
    K, y, a0, f0, act = _subproblem()
    with pytest.raises(ValueError, match="wss must be"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, wss=3)


def test_inner_smo_layouts_bitwise_identical():
    """The packed (q//128, 128) and flat (1, q) kernel layouts must follow
    bitwise-identical trajectories — flat is the hardware-proven lowering
    fallback, so any divergence would make a fallback silently change
    results."""
    K, y, a0, f0, act = _subproblem(q=256, seed=3)
    for wss in (1, 2):
        a_p, n_p, _, r_p = inner_smo_pallas(
            K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=300,
            interpret=True, wss=wss, layout="packed")
        a_f, n_f, _, r_f = inner_smo_pallas(
            K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=300,
            interpret=True, wss=wss, layout="flat")
        assert int(n_p) == int(n_f) and int(r_p) == int(r_f)
        np.testing.assert_array_equal(np.asarray(a_p), np.asarray(a_f))


def test_inner_smo_rejects_bad_layout():
    K, y, a0, f0, act = _subproblem()
    with pytest.raises(ValueError, match="layout must be"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, layout="ragged")


@pytest.mark.parametrize("n,q,block", [
    (1000, 64, 256),    # n not divisible by block (masked final write)
    (256, 128, 1024),   # block clamps to n
    (777, 32, 128),     # odd everything
])
def test_fused_fupdate_matches_xla_contraction(n, q, block):
    """rbf_cross_matvec_pallas (interpret) vs the XLA contraction it
    replaces, across block-boundary shapes — the masked final-block
    write and the no-padded-copy design must not leak out-of-bounds
    lanes into real rows. Derisks flipping fused_fupdate on once
    hardware timing exists (VERDICT r2 #3 is hardware-blocked)."""
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    from tpusvm.ops.rbf import rbf_cross_matvec

    rng = np.random.default_rng(n + q)
    X = jnp.asarray(rng.random((n, 16)), jnp.float32)
    XB = jnp.asarray(rng.random((q, 16)), jnp.float32)
    coef = jnp.asarray(rng.standard_normal(q), jnp.float32)
    ref = rbf_cross_matvec(X, XB, coef, 0.25)
    got = rbf_cross_matvec_pallas(X, XB, coef, 0.25, block=block,
                                  interpret=True)
    assert got.shape == (n,) and got.dtype == X.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fused_fupdate_traced_gamma_and_sn():
    """gamma is traced (SMEM-delivered) and a precomputed sn must give
    the same result as the internally computed one."""
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    from tpusvm.ops.rbf import sq_norms

    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.random((300, 8)), jnp.float32)
    XB = jnp.asarray(rng.random((64, 8)), jnp.float32)
    coef = jnp.asarray(rng.standard_normal(64), jnp.float32)
    a = rbf_cross_matvec_pallas(X, XB, coef, 0.5, interpret=True)
    b = rbf_cross_matvec_pallas(X, XB, coef, jnp.float32(0.5),
                                sn=sq_norms(X), interpret=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_fused_fupdate_auto_block():
    """Pins the VMEM-aware block sizing (the block=None default that
    replaced the OOMing block=1024): calibrated bench shape, wide-feature
    shrink, and the clear too-big-for-VMEM error instead of a Mosaic
    compile OOM. Model calibration evidence is in _auto_block's docstring
    (q=2048/d=784 hardware compile probes, round 4)."""
    from tpusvm.ops.pallas.fused_fupdate import _auto_block

    assert _auto_block(2048, 784) == 256       # bench shape: measured fit
    assert _auto_block(1024, 784) == 512       # narrower q -> bigger block
    assert _auto_block(256, 4096) == 512       # wide d shrinks the block
    assert _auto_block(64, 64) == 1024         # tiny problems hit the cap
    with pytest.raises(ValueError, match="XLA contraction"):
        _auto_block(8192, 4096)                # resident XB^T > VMEM
    with pytest.raises(ValueError, match="XLA contraction"):
        _auto_block(16384, 256)                # floor block busts the stack
    assert _auto_block(16384, 256, n=32) == 32  # small n lowers the floor
    # interpret mode must NOT raise on chip-infeasible shapes: the solver's
    # off-TPU fused path (interpret=True) falls back to the flat default
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    rng = np.random.default_rng(5)
    X = jnp.asarray(rng.random((64, 256)), jnp.float32)
    XB = jnp.asarray(rng.random((16384, 256)), jnp.float32)
    coef = jnp.zeros((16384,), jnp.float32)
    out = rbf_cross_matvec_pallas(X, XB, coef, 0.1, interpret=True)
    assert out.shape == (64,)
