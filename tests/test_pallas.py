"""Pallas kernel tests (interpret mode — runs on the CPU test mesh)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.ops.pallas import rbf_two_rows
from tpusvm.ops.rbf import rbf_rows_at


def test_two_rows_matches_xla():
    rng = np.random.default_rng(0)
    n, d = 1024, 256
    X = jnp.asarray(rng.random((n, d)), jnp.float32)
    idx = jnp.asarray([3, 777], jnp.int32)
    out = rbf_two_rows(X, X[idx], 0.5, interpret=True)
    ref = rbf_rows_at(X, idx, 0.5)
    np.testing.assert_allclose(
        np.asarray(out.T), np.asarray(ref), atol=2e-6
    )


def test_two_rows_rejects_unaligned():
    X = jnp.zeros((1000, 256), jnp.float32)  # n not a TILE_N multiple
    with pytest.raises(ValueError, match="pad first"):
        rbf_two_rows(X, X[:2], 0.5, interpret=True)
