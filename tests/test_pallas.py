"""Pallas kernel tests (interpret mode — runs on the CPU test mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.ops.pallas.inner_smo import inner_smo_pallas
from tpusvm.ops.rbf import rbf_cross
from tpusvm.solver.blocked import _inner_smo, blocked_smo_solve
from tpusvm.status import Status


def _subproblem(q=128, seed=0, d=8, gamma=0.5):
    rng = np.random.default_rng(seed)
    X = rng.random((q, d)).astype(np.float32)
    y = np.where(rng.random(q) < 0.5, 1, -1).astype(np.int32)
    K = rbf_cross(jnp.asarray(X), jnp.asarray(X), gamma)
    a0 = jnp.zeros(q, jnp.float32)
    f0 = -jnp.asarray(y, jnp.float32)
    act = jnp.ones(q, bool)
    return K, jnp.asarray(y), a0, f0, act


def test_inner_smo_pallas_invariants():
    """Box feasibility, sum(y*a) conservation, dual ascent."""
    K, y, a0, f0, act = _subproblem()
    C = 10.0
    a, n_upd, progress, reason = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=512, interpret=True
    )
    a = np.asarray(a)
    assert int(n_upd) > 0 and bool(progress)
    assert (a >= -1e-6).all() and (a <= C + 1e-6).all()
    # every 2-variable update preserves sum(y*a); started at 0
    np.testing.assert_allclose(float(np.sum(a * np.asarray(y))), 0.0, atol=1e-3)
    # dual objective W(a) = sum(a) - 0.5 a^T Q a must have increased from 0
    Q = np.asarray(K) * np.outer(np.asarray(y), np.asarray(y))
    dual = a.sum() - 0.5 * a @ Q @ a
    assert dual > 0.1
    assert int(reason) in (
        Status.CONVERGED, Status.NO_WORKING_SET, Status.MAX_ITER
    )


@pytest.mark.parametrize("q", [128, 256])
def test_inner_smo_pallas_matches_xla_before_bailout(q):
    """With no numerical bail-outs, the f32 trajectories are identical.

    q=128 is the degenerate single-row layout (R=1); q=256 exercises the
    multi-row (R, 128) sublane-packed layout, whose row-major index
    mapping must preserve the (1, q) first-occurrence tie-breaks."""
    K, y, a0, f0, act = _subproblem(q=q, seed=3)
    a_x, n_x, _, r_x = _inner_smo(K, y, a0, f0, act, 10.0, 1e-12, 1e-5, 200)
    a_p, n_p, _, r_p = inner_smo_pallas(
        K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=200, interpret=True
    )
    # the XLA engine hit its cap cleanly (no stall/infeasible/eta bail-out),
    # so shrinking never engaged and the two runs are the same sequence
    assert int(r_x) == Status.MAX_ITER, Status(int(r_x)).name
    assert int(n_x) == int(n_p) == 200
    np.testing.assert_array_equal(np.asarray(a_x), np.asarray(a_p))


def test_inner_smo_pallas_rejects_unaligned():
    K, y, a0, f0, act = _subproblem(q=100)
    with pytest.raises(ValueError, match="q % 128"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True)


def test_blocked_pallas_engine_matches_xla_solution():
    """Same optimum (solution-level parity) from both inner engines."""
    rng = np.random.default_rng(42)
    n, d = 256, 16
    X = jnp.asarray(rng.random((n, d)), jnp.float32)
    Y = jnp.asarray(np.where(rng.random(n) < 0.5, 1, -1), jnp.int32)
    kw = dict(C=10.0, gamma=1.0, tau=1e-5, q=128, max_inner=256,
              max_outer=500, accum_dtype=jnp.float64)
    r_x = blocked_smo_solve(X, Y, inner="xla", **kw)
    r_p = blocked_smo_solve(X, Y, inner="pallas", **kw)
    assert int(r_x.status) == Status.CONVERGED
    assert int(r_p.status) == Status.CONVERGED
    np.testing.assert_allclose(float(r_p.b), float(r_x.b), atol=5e-4)
    sv_x = np.asarray(r_x.alpha) > 1e-8
    sv_p = np.asarray(r_p.alpha) > 1e-8
    # SV sets agree up to tau-level boundary cases
    assert (sv_x != sv_p).mean() < 0.02
    np.testing.assert_allclose(
        np.asarray(r_p.alpha), np.asarray(r_x.alpha), atol=2e-3
    )


def test_blocked_rejects_bad_inner():
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="inner must be"):
        blocked_smo_solve(X, Y, inner="cuda")
    with pytest.raises(ValueError, match="multiple of 128"):
        blocked_smo_solve(X, Y, inner="pallas", q=16)


def test_inner_smo_wss2_same_optimum():
    """Second-order partner selection reaches the same subproblem optimum
    as first-order (different trajectory), in fewer or equal updates."""
    K, y, a0, f0, act = _subproblem(q=128, seed=5)
    C = 10.0
    a1, n1, _, _ = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=4096, interpret=True,
        wss=1)
    a2, n2, _, _ = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=4096, interpret=True,
        wss=2)
    Q = np.asarray(K) * np.outer(np.asarray(y), np.asarray(y))

    def dual(a):
        a = np.asarray(a)
        return a.sum() - 0.5 * a @ Q @ a

    assert int(n2) <= int(n1)
    # wss1 can end slightly short of the optimum when f32 shrinking
    # deactivates stalled violators; wss2 must be at least as good and
    # within the same tau-limited band
    assert dual(a2) >= dual(a1) - 1e-3
    np.testing.assert_allclose(dual(a2), dual(a1), rtol=1e-3)
    # sum(y*a) conservation holds for the second-order trajectory too
    np.testing.assert_allclose(float(np.sum(np.asarray(a2) * np.asarray(y))),
                               0.0, atol=1e-3)


def test_inner_smo_rejects_bad_wss():
    K, y, a0, f0, act = _subproblem()
    with pytest.raises(ValueError, match="wss must be"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, wss=3)


def test_inner_smo_eta_exclude_matches_xla_wss2():
    """eta_exclude folds the XLA engine's degenerate-partner exclusion
    into the kernel (VERDICT r4 #5): on data with no degenerate pairs the
    two engines now share the SAME selection rule, so their f32
    trajectories agree (the kernel reconstructs f[i_l] from the selected
    gain, so agreement is to f32 rounding, not bitwise)."""
    K, y, a0, f0, act = _subproblem(q=256, seed=3)
    a_x, n_x, _, r_x = _inner_smo(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                                  200, wss=2)
    a_p, n_p, _, r_p = inner_smo_pallas(
        K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=200,
        interpret=True, wss=2, eta_exclude=True)
    assert int(r_x) == Status.MAX_ITER, Status(int(r_x)).name
    assert int(n_x) == int(n_p) == 200
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_x), atol=1e-3)


def test_inner_smo_eta_exclude_degenerate_partners():
    """Near-coincident points (the fuzz-seed-4047 class): degenerate
    partners must not poison the eta_exclude gain selection — the kernel
    falls back to the first-order pick and shrinks dead pairs, finishing
    with a feasible subproblem optimum at least as good as the XLA
    engine's (which ends the subproblem on the first dead pair)."""
    rng = np.random.default_rng(4047)
    q, d = 128, 4
    Xb = rng.random((q // 2, d)).astype(np.float32)
    X = np.repeat(Xb, 2, axis=0)  # exact duplicates -> eta == 0 pairs
    y = np.where(rng.random(q) < 0.5, 1, -1).astype(np.int32)
    K = rbf_cross(jnp.asarray(X), jnp.asarray(X), 0.5)
    a0 = jnp.zeros(q, jnp.float32)
    f0 = -jnp.asarray(y, jnp.float32)
    act = jnp.ones(q, bool)
    C = 10.0
    a_p, n_p, prog, r_p = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=4096,
        interpret=True, wss=2, eta_exclude=True)
    a_p = np.asarray(a_p)
    assert np.isfinite(a_p).all()
    assert (a_p >= -1e-6).all() and (a_p <= C + 1e-6).all()
    np.testing.assert_allclose(float(np.sum(a_p * y)), 0.0, atol=1e-3)
    assert int(r_p) in (
        Status.CONVERGED, Status.NO_WORKING_SET, Status.MAX_ITER
    )
    Q = np.asarray(K) * np.outer(y, y)
    dual_p = a_p.sum() - 0.5 * a_p @ Q @ a_p
    a_x, _, _, _ = _inner_smo(K, jnp.asarray(y), a0, f0, act, C, 1e-12,
                              1e-5, 4096, wss=2)
    a_x = np.asarray(a_x)
    dual_x = a_x.sum() - 0.5 * a_x @ Q @ a_x
    assert dual_p >= dual_x - 1e-3


def test_inner_smo_eta_exclude_layouts_identical():
    """The eta_exclude selection must be layout-invariant like the rest
    of the kernel (row-major index mapping preserves tie-breaks)."""
    K, y, a0, f0, act = _subproblem(q=256, seed=11)
    a_pk, n_pk, _, r_pk = inner_smo_pallas(
        K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=300,
        interpret=True, wss=2, eta_exclude=True, layout="packed")
    a_fl, n_fl, _, r_fl = inner_smo_pallas(
        K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=300,
        interpret=True, wss=2, eta_exclude=True, layout="flat")
    assert int(n_pk) == int(n_fl) and int(r_pk) == int(r_fl)
    np.testing.assert_array_equal(np.asarray(a_pk), np.asarray(a_fl))


@pytest.mark.parametrize("p,q", [(2, 512), (4, 1024)])
def test_inner_smo_multipair_invariants(p, q):
    """The batched slot-pair kernel (VERDICT r4 #3): box feasibility,
    sum(y*a) conservation (each disjoint pair preserves it), dual ascent,
    and an optimum matching the sequential kernel's to the tau band.
    Alignment: p slots need (q//128) % (2p) == 0 -> p=2 at q=512,
    p=4 at q=1024."""
    K, y, a0, f0, act = _subproblem(q=q, seed=7)
    C = 10.0
    # budget sized for convergence: multipair's Jacobi slot updates
    # inflate the update count ~2-4x over the sequential trajectory
    a_m, n_m, prog, r_m = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=40000, interpret=True,
        multipair=p)
    a_m = np.asarray(a_m)
    assert int(n_m) > 0 and bool(prog)
    # box tolerance 5e-6, not the sequential test's 1e-6: a_h_new is
    # deliberately unclipped (the reference's exact update; feasible in
    # exact arithmetic) and the multipair trajectory's higher update
    # count accumulates a couple more f32 ulps at C=10
    assert (a_m >= -5e-6).all() and (a_m <= C + 5e-6).all()
    np.testing.assert_allclose(float(np.sum(a_m * np.asarray(y))), 0.0,
                               atol=1e-3)
    assert int(r_m) in (
        Status.CONVERGED, Status.NO_WORKING_SET, Status.MAX_ITER
    )
    a_1, _, _, _ = inner_smo_pallas(
        K, y, a0, f0, act, C, 1e-12, 1e-5, max_inner=40000, interpret=True)
    Q = np.asarray(K) * np.outer(np.asarray(y), np.asarray(y))

    def dual(a):
        a = np.asarray(a)
        return a.sum() - 0.5 * a @ Q @ a

    assert dual(a_m) > 0.1
    # single-subproblem convergence comparison at an UNBOUNDED budget is
    # deliberately loose (5%): the kernel's f is f32 and never
    # reconstructed within a subproblem, so the inflated multipair
    # update count accumulates more drift before the measured gap
    # closes (sequential ~10k updates vs multipair ~22k at q=1024).
    # Production bounds max_inner per round and the outer loop rebuilds
    # f in the accum dtype — the real parity bar is the end-to-end
    # blocked test below and the pallas-mp fuzz mode.
    np.testing.assert_allclose(dual(a_m), dual(a_1), rtol=5e-2)


def test_blocked_multipair_matches_xla_solution():
    """End-to-end blocked solve with the multipair kernel: same optimum
    as the XLA engine (solution-level parity, the cross-engine bar)."""
    rng = np.random.default_rng(17)
    n, d = 600, 12
    X = jnp.asarray(rng.random((n, d)), jnp.float32)
    Y = jnp.asarray(np.where(rng.random(n) < 0.5, 1, -1), jnp.int32)
    kw = dict(C=10.0, gamma=1.0, tau=1e-5, q=512, max_inner=2048,
              max_outer=500, accum_dtype=jnp.float64, wss=1)
    r_x = blocked_smo_solve(X, Y, inner="xla", **kw)
    # q=512 -> R=4 rows: p=2 is the valid slot partition
    r_m = blocked_smo_solve(X, Y, inner="pallas", pallas_multipair=2, **kw)
    assert int(r_x.status) == Status.CONVERGED
    assert int(r_m.status) == Status.CONVERGED
    np.testing.assert_allclose(float(r_m.b), float(r_x.b), atol=2e-3)
    sv_x = np.asarray(r_x.alpha) > 1e-8
    sv_m = np.asarray(r_m.alpha) > 1e-8
    assert (sv_x != sv_m).mean() < 0.02
    np.testing.assert_allclose(
        np.asarray(r_m.alpha), np.asarray(r_x.alpha), atol=5e-3
    )


def test_inner_smo_multipair_cross_slot_global_ends():
    """ADVICE r5 #4 adversarial case: the global pair's ends live in
    DIFFERENT slots whose updates touch them in the same iteration.

    Construction (q=512, p=2 -> 4 packed rows, one per slot-half): labels
    +1 on [0, 384) and -1 on [384, 512) with a0 = 0 and f0 = -y put the
    globally-worst I_high member at index 0 (slot 0's high row) and the
    globally-worst I_low member at index 384 (slot 1's low row). Slot 0's
    low row [256, 384) is all-positive with a = 0 — not in I_low — so
    slot 0 idles while slot 1 pairs its own high row with index 384:
    the global pair's low end is touched by a slot, the high end is not,
    and the pre-fix kernel then applied the global step with
    iteration-start b_h/b_l against the post-slot alpha at 384 — a
    box-clipped but potentially non-ascent step that inflates the update
    count. Post-fix the global step is skipped on such iterations, and
    the invariants below must hold with the update count comparable to
    the sequential kernel's, never spinning toward the 40k cap.

    Duplicated points additionally seed degenerate (eta == 0) pairs, the
    shrink path's adversarial food (fuzz-seed-4047 class)."""
    q, d, p = 512, 6, 2
    rng = np.random.default_rng(4047)
    Xb = rng.random((q // 2, d)).astype(np.float32)
    X = np.repeat(Xb, 2, axis=0)  # exact duplicates -> eta == 0 pairs
    y = np.where(np.arange(q) < 384, 1, -1).astype(np.int32)
    K = rbf_cross(jnp.asarray(X), jnp.asarray(X), 0.5)
    a0 = jnp.zeros(q, jnp.float32)
    f0 = -jnp.asarray(y, jnp.float32)
    act = jnp.ones(q, bool)
    C = 10.0
    a_m, n_m, prog, r_m = inner_smo_pallas(
        K, jnp.asarray(y), a0, f0, act, C, 1e-12, 1e-5, max_inner=40000,
        interpret=True, multipair=p)
    a_m = np.asarray(a_m)
    assert bool(prog)
    assert np.isfinite(a_m).all()
    assert (a_m >= -5e-6).all() and (a_m <= C + 5e-6).all()
    np.testing.assert_allclose(float(np.sum(a_m * y)), 0.0, atol=1e-3)
    assert int(r_m) in (
        Status.CONVERGED, Status.NO_WORKING_SET, Status.MAX_ITER
    )
    # the sequential kernel on the same subproblem: the multipair
    # trajectory may legitimately cost more updates (Jacobi slots), but
    # the pre-fix non-ascent global steps inflated it toward the cap —
    # bound it at a small multiple, far below max_inner
    a_1, n_1, _, _ = inner_smo_pallas(
        K, jnp.asarray(y), a0, f0, act, C, 1e-12, 1e-5, max_inner=40000,
        interpret=True)
    assert int(n_m) < 6 * int(n_1), (int(n_m), int(n_1))
    assert int(n_m) < 40000  # never rides the budget cap
    Q = np.asarray(K) * np.outer(y, y)

    def dual(a):
        a = np.asarray(a)
        return a.sum() - 0.5 * a @ Q @ a

    assert dual(a_m) > 0.1
    np.testing.assert_allclose(dual(a_m), dual(a_1), rtol=5e-2)


def test_inner_smo_multipair_validation():
    K, y, a0, f0, act = _subproblem(q=256, seed=2)
    with pytest.raises(ValueError, match="multipair requires wss=1"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, wss=2, multipair=2)
    with pytest.raises(ValueError, match="multipair requires layout"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, layout="flat",
                         multipair=2)
    # q=256 -> R=2 rows; p=2 needs R % 4 == 0
    with pytest.raises(ValueError, match="rows per slot"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, multipair=2)


def test_blocked_multipair_rejects_xla_engine():
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="pallas-engine feature"):
        # deliberate invalid combo under pytest.raises
        blocked_smo_solve(X, Y, inner="xla",  # tpusvm: disable=JX008
                          pallas_multipair=4)


def test_blocked_eta_exclude_rejects_xla_engine():
    """ADVICE r5: pallas_eta_exclude=True resolving to a non-pallas inner
    engine used to be silently ignored — an A/B run could record
    eta_exclude=true while measuring the plain XLA engine. Now it raises
    via the shared flag-compatibility table (tpusvm.config)."""
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="pallas-engine feature"):
        # deliberate invalid combo under pytest.raises
        blocked_smo_solve(X, Y, inner="xla", wss=2,  # tpusvm: disable=JX008
                          pallas_eta_exclude=True)
    # inner='auto' off-TPU resolves to xla — same rejection, so a
    # CPU-pinned probe cannot mislabel its rows
    if jax.default_backend() != "tpu":
        with pytest.raises(ValueError, match="pallas-engine feature"):
            blocked_smo_solve(X, Y, wss=2, pallas_eta_exclude=True)


def test_blocked_layout_rejects_xla_engine():
    X = jnp.zeros((16, 4), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="pallas-engine feature"):
        # deliberate invalid combo under pytest.raises
        blocked_smo_solve(X, Y, inner="xla",  # tpusvm: disable=JX008
                          pallas_layout="flat")


def test_inner_smo_eta_exclude_rejects_wss1():
    K, y, a0, f0, act = _subproblem()
    with pytest.raises(ValueError, match="eta_exclude"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, wss=1,
                         eta_exclude=True)


def test_inner_smo_layouts_bitwise_identical():
    """The packed (q//128, 128) and flat (1, q) kernel layouts must follow
    bitwise-identical trajectories — flat is the hardware-proven lowering
    fallback, so any divergence would make a fallback silently change
    results."""
    K, y, a0, f0, act = _subproblem(q=256, seed=3)
    for wss in (1, 2):
        a_p, n_p, _, r_p = inner_smo_pallas(
            K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=300,
            interpret=True, wss=wss, layout="packed")
        a_f, n_f, _, r_f = inner_smo_pallas(
            K, y, a0, f0, act, 10.0, 1e-12, 1e-5, max_inner=300,
            interpret=True, wss=wss, layout="flat")
        assert int(n_p) == int(n_f) and int(r_p) == int(r_f)
        np.testing.assert_array_equal(np.asarray(a_p), np.asarray(a_f))


def test_inner_smo_rejects_bad_layout():
    K, y, a0, f0, act = _subproblem()
    with pytest.raises(ValueError, match="layout must be"):
        inner_smo_pallas(K, y, a0, f0, act, 10.0, 1e-12, 1e-5,
                         max_inner=64, interpret=True, layout="ragged")


@pytest.mark.parametrize("n,q,block", [
    (1000, 64, 256),    # n not divisible by block (masked final write)
    (256, 128, 1024),   # block clamps to n
    (777, 32, 128),     # odd everything
])
def test_fused_fupdate_matches_xla_contraction(n, q, block):
    """rbf_cross_matvec_pallas (interpret) vs the XLA contraction it
    replaces, across block-boundary shapes — the masked final-block
    write and the no-padded-copy design must not leak out-of-bounds
    lanes into real rows. Derisks flipping fused_fupdate on once
    hardware timing exists (VERDICT r2 #3 is hardware-blocked)."""
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    from tpusvm.ops.rbf import rbf_cross_matvec

    rng = np.random.default_rng(n + q)
    X = jnp.asarray(rng.random((n, 16)), jnp.float32)
    XB = jnp.asarray(rng.random((q, 16)), jnp.float32)
    coef = jnp.asarray(rng.standard_normal(q), jnp.float32)
    ref = rbf_cross_matvec(X, XB, coef, 0.25)
    got = rbf_cross_matvec_pallas(X, XB, coef, 0.25, block=block,
                                  interpret=True)
    assert got.shape == (n,) and got.dtype == X.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fused_fupdate_traced_gamma_and_sn():
    """gamma is traced (SMEM-delivered) and a precomputed sn must give
    the same result as the internally computed one."""
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    from tpusvm.ops.rbf import sq_norms

    rng = np.random.default_rng(3)
    X = jnp.asarray(rng.random((300, 8)), jnp.float32)
    XB = jnp.asarray(rng.random((64, 8)), jnp.float32)
    coef = jnp.asarray(rng.standard_normal(64), jnp.float32)
    a = rbf_cross_matvec_pallas(X, XB, coef, 0.5, interpret=True)
    b = rbf_cross_matvec_pallas(X, XB, coef, jnp.float32(0.5),
                                sn=sq_norms(X), interpret=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_fused_fupdate_auto_block():
    """Pins the VMEM-aware block sizing (the block=None default that
    replaced the OOMing block=1024): calibrated bench shape, wide-feature
    shrink, and the clear too-big-for-VMEM error instead of a Mosaic
    compile OOM. Model calibration evidence is in _auto_block's docstring
    (q=2048/d=784 hardware compile probes, round 4)."""
    from tpusvm.ops.pallas.fused_fupdate import _auto_block

    assert _auto_block(2048, 784) == 256       # bench shape: measured fit
    assert _auto_block(1024, 784) == 512       # narrower q -> bigger block
    assert _auto_block(256, 4096) == 512       # wide d shrinks the block
    assert _auto_block(64, 64) == 1024         # tiny problems hit the cap
    with pytest.raises(ValueError, match="XLA contraction"):
        _auto_block(8192, 4096)                # resident XB^T > VMEM
    with pytest.raises(ValueError, match="XLA contraction"):
        _auto_block(16384, 256)                # floor block busts the stack
    assert _auto_block(16384, 256, n=32) == 32  # small n lowers the floor
    # interpret mode must NOT raise on chip-infeasible shapes: the solver's
    # off-TPU fused path (interpret=True) falls back to the flat default
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    rng = np.random.default_rng(5)
    X = jnp.asarray(rng.random((64, 256)), jnp.float32)
    XB = jnp.asarray(rng.random((16384, 256)), jnp.float32)
    coef = jnp.zeros((16384,), jnp.float32)
    out = rbf_cross_matvec_pallas(X, XB, coef, 0.1, interpret=True)
    assert out.shape == (64,)
