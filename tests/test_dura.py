"""Tests for tpusvm.analysis.dura — the two-armed durability auditor.

Static arm: every JXD rule fires on its known-bad corpus snippet under
tests/analysis_corpus/dura/ (and nothing else fires there), the
write-protocol model extraction is right, durable-by suppressions
document their invariant, the baseline grandfathers, the AST-parsed
fault-point universe matches the runtime registry, and the repo itself
lints JXD-clean against the committed EMPTY baseline.

Dynamic arm: the derived point universe is fully claimed by the
recovery scenarios, the generated kill-window plan is byte-identical
per seed, a real kill window recovers to the control digest, and the
journal/commit hot paths fsync their staged bytes before renaming
(pinned with a monkeypatched os.fsync).
"""

import json
from pathlib import Path

import pytest

from tpusvm.analysis.dura import (
    DURA_RULE_SUMMARIES,
    DURABLE_MODULES,
    all_dura_rules,
    dura_lint_file,
    dura_lint_paths,
    dura_lint_source,
    registered_points,
)

REPO = Path(__file__).resolve().parent.parent
CORPUS = REPO / "tests" / "analysis_corpus" / "dura"
DURA_RULE_IDS = ("JXD301", "JXD302", "JXD303", "JXD304", "JXD305",
                 "JXD306")


# ---------------------------------------------------------------- registry
def test_registry_has_all_dura_rules():
    rules = all_dura_rules()
    assert tuple(sorted(rules)) == DURA_RULE_IDS
    for rid, rule in rules.items():
        assert rule.id == rid
        assert rule.summary
    assert set(DURA_RULE_SUMMARIES) == set(DURA_RULE_IDS)


def test_unknown_select_is_rejected():
    with pytest.raises(ValueError, match="unknown dura rule"):
        dura_lint_source("x = 1\n", select={"JXD999"})


def test_durable_module_registry_names_real_files():
    for suffix in DURABLE_MODULES:
        assert (REPO / suffix).exists(), (
            f"DURABLE_MODULES names {suffix}, which does not exist — "
            "keep the registry in step with the tree"
        )


# ------------------------------------------------------------------ corpus
@pytest.mark.parametrize("rule_id", DURA_RULE_IDS)
def test_rule_fires_on_its_corpus_snippet(rule_id):
    matches = sorted(CORPUS.glob(f"{rule_id.lower()}_*.py"))
    assert matches, f"no dura corpus file for {rule_id}"
    findings, _ = dura_lint_file(matches[0])
    fired = {f.rule for f in findings}
    assert rule_id in fired, (
        f"{rule_id} did not fire on {matches[0].name}; got {fired}"
    )
    # single-hazard by construction: a precision regression in ANY rule
    # shows up as an extra id here
    assert fired == {rule_id}, (
        f"extra rules fired on {matches[0].name}: {fired - {rule_id}}"
    )


def test_clean_corpus_is_clean():
    findings, suppressed = dura_lint_file(CORPUS / "clean.py")
    assert findings == []
    assert suppressed == []


def test_corpus_findings_are_located():
    for f in CORPUS.glob("jxd*.py"):
        findings, _ = dura_lint_file(f)
        for finding in findings:
            assert finding.line >= 1 and finding.col >= 1
            assert finding.snippet
            assert finding.fingerprint and len(finding.fingerprint) == 12


def test_parse_failure_is_a_finding():
    findings, _ = dura_lint_source("def broken(:\n")
    assert [f.rule for f in findings] == ["JXD300"]


# ----------------------------------------------------------- model extraction
def _model(src: str, path: str = "<test>"):
    from tpusvm.analysis.context import ModuleContext
    from tpusvm.analysis.dura.model import DuraModel

    return DuraModel(ModuleContext(path, src))


_MODEL_SRC = '''
import io
import json
import os

from tpusvm import faults

VERSION = 2


def commit(path, payload):
    faults.point("models.save", path=path)
    obj = {"format_version": VERSION, "rows": payload}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def read(path):
    with open(path) as f:
        obj = json.load(f)
    if obj.get("format_version") != VERSION:
        raise ValueError(path)
    return obj["rows"]


def buffered():
    import numpy as np
    buf = io.BytesIO()
    np.savez(buf, x=1)
'''


def test_model_extraction():
    m = _model(_MODEL_SRC)
    by_name = {s.name: s for s in m.scopes}
    commit = by_name["commit"]
    # one staged write + one replace; open(path) in read() has mode "r"
    assert len(commit.writes) == 1 and commit.writes[0].mode == "w"
    assert len(commit.replaces) == 1 and not commit.replaces[0].fsynced
    assert by_name["read"].writes == []
    # the version field is written AND gated
    assert ("format_version", ) == tuple(k for k, _ in m.version_writes)
    assert "format_version" in m.read_keys
    assert m.has_readers
    # the point literal is extracted; the commit site is covered
    assert [lit for _, lit in m.point_calls] == ["models.save"]
    assert m.point_covered(commit.replaces[0].node)
    # savez onto a BytesIO is not a durable write
    assert by_name["buffered"].writes == []
    # the staged write is recognised as covered by the rename protocol
    assert m.write_is_staged(commit.writes[0], commit)


def test_durable_status_registry_and_pragma():
    from tpusvm.analysis.dura.model import durable_status

    assert durable_status("tpusvm/stream/format.py", "") == (True, True)
    assert durable_status("tpusvm/serve/cache.py", "") == (True, False)
    assert durable_status("x.py", "# tpusvm: durable-protocol\n") == \
        (True, False)
    assert durable_status(
        "x.py", "# tpusvm: durable-protocol=kill-safe\n") == (True, True)
    assert durable_status("x.py", "") == (False, False)


def test_dir_identity_shapes():
    src = (
        "import os\nimport tempfile\n\n"
        "def f(out_dir, path):\n"
        "    a = os.path.join(out_dir, 'x.tmp')\n"
        "    b = os.path.join(tempfile.gettempdir(), 'x.tmp')\n"
        "    c = path + '.tmp'\n"
        "    os.replace(a, os.path.join(out_dir, 'x'))\n"
        "    os.replace(b, os.path.join(out_dir, 'y'))\n"
        "    os.replace(c, path)\n"
    )
    m = _model(src)
    scope = {s.name: s for s in m.scopes}["f"]
    idents = [
        (m.dir_identity(r.src, scope), m.dir_identity(r.dst, scope))
        for r in sorted(scope.replaces, key=lambda r: r.node.lineno)
    ]
    assert idents[0][0] == idents[0][1] == ("join", "out_dir")
    assert idents[1][0][0] == "tempfile" and idents[1][1][0] == "join"
    assert idents[2][0] == idents[2][1] == ("sibling", "dir(path)")


# ------------------------------------------------------------ suppressions
_BAD = ("import json\n\n"
        "def save(path, obj):\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump(obj, f)\n")


def test_durable_by_annotation_suppresses_and_documents():
    src = _BAD.replace(
        "    with open(path, 'w') as f:",
        "    # tpusvm: durable-by=single-writer scratch file, re-derived"
        " on any read error\n"
        "    with open(path, 'w') as f:")
    active, suppressed = dura_lint_source(src)
    assert active == []
    assert [f.rule for f in suppressed] == ["JXD301"]


def test_empty_durable_by_does_not_suppress():
    src = _BAD.replace(
        "    with open(path, 'w') as f:",
        "    # tpusvm: durable-by=\n"
        "    with open(path, 'w') as f:")
    active, _ = dura_lint_source(src)
    assert [f.rule for f in active] == ["JXD301"]


def test_disable_comment_also_works():
    src = _BAD.replace(
        "    with open(path, 'w') as f:",
        "    with open(path, 'w') as f:  # tpusvm: disable=JXD301")
    active, suppressed = dura_lint_source(src)
    assert active == []
    assert [f.rule for f in suppressed] == ["JXD301"]


# ---------------------------------------------------------------- baseline
def test_baseline_grandfathers_dura_findings(tmp_path):
    from tpusvm.analysis.baseline import load_baseline, write_baseline

    target = CORPUS / "jxd301_unstaged_write.py"
    findings, _ = dura_lint_file(target)
    assert findings
    bl = tmp_path / "dura_bl.json"
    write_baseline(bl, findings)
    result = dura_lint_paths([str(target)], baseline=load_baseline(bl))
    assert result.findings == []
    assert len(result.baselined) == len(findings)
    assert result.exit_code == 0


def test_committed_dura_baseline_is_empty():
    from tpusvm.analysis.baseline import load_baseline

    path = REPO / ".tpusvm-dura-baseline.json"
    assert path.exists(), "committed dura baseline is missing"
    assert load_baseline(path) == set(), (
        "the dura baseline must stay EMPTY — fix findings or suppress "
        "them with a documented durable-by annotation"
    )


# ---------------------------------------------------------- repo dura gate
def test_repo_lints_dura_clean():
    """The CI dura gate, in-process: the repo's own trees produce zero
    unsuppressed JXD findings (the trace rotation and the fsync_replace
    helper itself carry documented durable-by annotations)."""
    result = dura_lint_paths(
        [str(REPO / "tpusvm"), str(REPO / "benchmarks"),
         str(REPO / "scripts"), str(REPO / "bench.py")])
    assert result.findings == [], "\n".join(
        f.render() for f in result.findings)
    assert result.files_scanned > 50
    assert len(result.suppressed) >= 2


# ----------------------------------------------------- fault-point universe
def test_registered_points_parse_matches_runtime():
    """The lint arm AST-parses POINTS so it never imports numpy; this
    pins the parse against the imported runtime set (drift here would
    silently disable the JXD303 cross-check)."""
    from tpusvm.faults.injection import POINTS

    assert registered_points() == POINTS


def test_new_commit_points_are_registered():
    from tpusvm.faults.injection import POINTS

    for point in ("stream.journal", "models.save", "serve.state_write",
                  "autopilot.state", "cascade.checkpoint"):
        assert point in POINTS


def test_uncovered_commit_in_durable_module_fires():
    src = ("# tpusvm: durable-protocol\n"
           "import json\nimport os\n\n"
           "def commit(path, obj):\n"
           "    tmp = path + '.tmp'\n"
           "    with open(tmp, 'w') as f:\n"
           "        json.dump(obj, f)\n"
           "    os.replace(tmp, path)\n")
    active, _ = dura_lint_source(src, select={"JXD303"})
    assert [f.rule for f in active] == ["JXD303"]


# ------------------------------------------------------------- dynamic arm
def test_derived_points_are_claimed_by_scenarios():
    """The coverage contract: every write-guarding point the static
    model derives must be claimed by some recovery scenario — adding a
    guarded durable write without matrix coverage fails here (and in
    derive_plan, and in CI)."""
    from tpusvm.analysis.dura.matrix import SCENARIOS, derive_points

    derived = derive_points()
    assert set(derived) == {
        "ingest.write_shard", "stream.journal", "stream.append",
        "solver.outer_checkpoint", "models.save", "serve.state_write",
        "autopilot.state", "cascade.checkpoint", "tenants.store",
        "pod.merge",
    }, "write-guarding point universe drifted — update the scenarios"
    claimed = set()
    for sc in SCENARIOS.values():
        claimed |= sc.points
    assert set(derived) <= claimed
    # read-side points never produce kill windows
    assert "cache.read" not in derived
    assert "stream.read_shard" not in derived


def test_derive_plan_is_deterministic_by_seed():
    """Same seed => byte-identical rendered plan (the reproduce-by-seed
    contract). Uses the cheap pure-python scenarios to keep the control
    runs fast."""
    from tpusvm.analysis.dura.matrix import derive_plan, render_plan

    names = ["autopilot_state", "serve_state"]
    a = render_plan(derive_plan(seed=7, scenarios=names))
    b = render_plan(derive_plan(seed=7, scenarios=names))
    assert a == b
    doc = json.loads(a)
    assert doc["kind"] == "tpusvm-dura-matrix-plan"
    assert doc["seed"] == 7
    assert doc["windows"], "control runs derived no kill windows"
    for w in doc["windows"]:
        assert w["at_hit"] >= 1 and w["point"] in doc["derived_points"]


def test_matrix_window_kills_and_recovers():
    """One real window end-to-end: the generated kill rule fires, the
    recovery run completes, and the recovered digest equals control."""
    from tpusvm.analysis.dura.matrix import derive_plan, run_matrix

    plan = derive_plan(seed=3, scenarios=["autopilot_state"],
                       max_windows=1)
    report = run_matrix(plan)
    assert report.results, "no windows ran"
    assert report.ok, report.render()
    assert "recovered == control" in report.render()


def test_matrix_scenario_docs_and_points():
    from tpusvm.analysis.dura.matrix import SCENARIOS

    for name, sc in SCENARIOS.items():
        assert sc.name == name
        assert sc.points and sc.doc


# ----------------------------------------------------- fsync-before-rename
def test_fsync_replace_syncs_before_renaming(tmp_path, monkeypatch):
    """The helper's contract: the staged fd is fsync'd, then renamed —
    pinned by spying both syscalls and asserting the order."""
    import os

    from tpusvm.utils.durable import fsync_replace

    events = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(
        os, "fsync", lambda fd: (events.append("fsync"), real_fsync(fd)))
    monkeypatch.setattr(
        os, "replace",
        lambda a, b: (events.append("replace"), real_replace(a, b)))
    tmp = tmp_path / "x.tmp"
    tmp.write_text("payload")
    fsync_replace(str(tmp), str(tmp_path / "x"))
    assert events == ["fsync", "replace"]
    assert (tmp_path / "x").read_text() == "payload"
    assert not tmp.exists()


def test_journal_hot_paths_fsync(tmp_path, monkeypatch):
    """The satellite pin: the ingest journal, the append commit and the
    autopilot state write all flush+fsync their staged bytes before the
    rename (a bare os.replace here regresses JXD306 kill-safety)."""
    import os

    import numpy as np

    from tpusvm.autopilot.state import AutopilotState, save_state
    from tpusvm.stream.append import append_blocks
    from tpusvm.stream.format import ingest_arrays

    counts = {"n": 0}
    real_fsync = os.fsync
    monkeypatch.setattr(
        os, "fsync",
        lambda fd: (counts.__setitem__("n", counts["n"] + 1),
                    real_fsync(fd))[1])

    rng = np.random.default_rng(0)
    X = rng.normal(size=(48, 3))
    Y = np.where(rng.random(48) < 0.5, 1, -1)
    ds = tmp_path / "ds"
    ingest_arrays(str(ds), X, Y, rows_per_shard=16)
    after_ingest = counts["n"]
    assert after_ingest > 0, "fresh ingest never fsync'd"

    append_blocks(str(ds), [(X[:8], Y[:8])])
    after_append = counts["n"]
    assert after_append > after_ingest, "append commit never fsync'd"

    save_state(str(tmp_path / "ap.json"), AutopilotState(seed=1))
    assert counts["n"] > after_append, "autopilot state never fsync'd"


# --------------------------------------------------------------------- CLI
def test_cli_dura_dispatch_and_exit_codes(capsys):
    from tpusvm.analysis.cli import main

    rc = main(["dura", str(CORPUS / "jxd301_unstaged_write.py"),
               "--no-baseline"])
    assert rc == 1
    assert "JXD301" in capsys.readouterr().out
    rc = main(["dura", str(CORPUS / "clean.py"), "--no-baseline"])
    assert rc == 0


def test_cli_dura_json_schema(capsys):
    from tpusvm.analysis.cli import main

    rc = main(["dura", str(CORPUS / "jxd305_journal_before_commit.py"),
               "--format", "json", "--no-baseline"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "tpusvm.analysis.dura"
    assert set(doc["rules"]) == set(DURA_RULE_IDS)
    assert doc["counts"]["JXD305"] == len(doc["findings"])
    for f in doc["findings"]:
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "snippet", "fingerprint"}


def test_cli_dura_list_rules(capsys):
    from tpusvm.analysis.cli import main

    assert main(["dura", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in DURA_RULE_IDS:
        assert rid in out


def test_cli_main_list_rules_includes_dura(capsys):
    from tpusvm.analysis.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "JXD301" in out and "[dura]" in out


def test_cli_dura_matrix_list_scenarios(capsys):
    from tpusvm.analysis.cli import main

    assert main(["dura-matrix", "--list-scenarios"]) == 0
    out = capsys.readouterr().out
    for name in ("ingest", "append", "checkpoint", "model_save",
                 "serve_state", "autopilot_state", "cascade_ckpt"):
        assert name in out


def test_cli_dura_matrix_unknown_scenario_is_usage_error(capsys):
    from tpusvm.analysis.cli import main

    rc = main(["dura-matrix", "--scenario", "nope", "--list-windows"])
    assert rc == 2
    assert "unknown scenario" in capsys.readouterr().err


def test_ci_has_dura_lint_and_matrix_steps():
    """The dura gates must be wired: a dura lint sweep over every Python
    root (empty-baseline diff), dura --list-rules in the no-jax lint
    job, the self-corpus derivation from all_dura_rules(), and the
    derived crash-window matrix smoke in the test job."""
    ci = (REPO / ".github" / "workflows" / "ci.yml").read_text(
        encoding="utf-8")
    dura_lines = [ln for ln in ci.splitlines()
                  if "tpusvm.analysis dura " in ln]
    sweep = " ".join(dura_lines)
    for root in ("tpusvm/", "benchmarks/", "scripts/", "bench.py"):
        assert root in sweep, (
            f"CI dura lint sweep is missing the {root} root: {sweep!r}")
    assert "dura --list-rules" in ci
    assert "all_dura_rules" in ci
    assert 'glob("tests/analysis_corpus/dura/*.py")' in ci
    assert "dura-matrix --smoke" in ci
