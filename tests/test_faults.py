"""tpusvm.faults tests: deterministic injection, retry/backoff, circuit
breaker, crash-safe training, journaled ingest, degraded-mode serving.

The acceptance contract (ISSUE 7): for every registered injection point,
a seeded plan (a) retries transient faults to success, (b) reproduces an
uninterrupted solve bit-for-bit after kill-at-checkpoint + resume, and
(c) sheds load / trips the breaker under injected scoring failures
without deadlocking — with the whole fault lifecycle visible in obs
counters and trace events.
"""

import json
import os
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm import faults
from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, rings
from tpusvm.models import BinarySVC
from tpusvm.obs.registry import MetricsRegistry
from tpusvm.status import ServeStatus, Status, StreamStatus


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test leaves the process with no active plan or sink."""
    yield
    faults.deactivate()
    faults.set_event_sink(None)


def _rule(**kw):
    return faults.FaultRule(**kw)


# ------------------------------------------------------------------ plan
def test_plan_rejects_unknown_points_kinds_and_versions(tmp_path):
    with pytest.raises(ValueError, match="unknown injection point"):
        faults.FaultPlan([_rule(point="nope.nope", kind="transient")])
    with pytest.raises(ValueError, match="unknown kind"):
        faults.FaultPlan([_rule(point="serve.score", kind="explode")])
    p = tmp_path / "plan.json"
    p.write_text("{}")
    with pytest.raises(ValueError, match="format_version"):
        faults.load_plan(str(p))
    p.write_text(json.dumps({"format_version": 99, "rules": []}))
    with pytest.raises(ValueError, match="unsupported fault plan"):
        faults.load_plan(str(p))
    p.write_text(json.dumps({
        "format_version": 1,
        "rules": [{"point": "serve.score", "kind": "latency",
                   "surprise": 1}],
    }))
    with pytest.raises(ValueError, match="unknown keys"):
        faults.load_plan(str(p))
    p.write_text("not json")
    with pytest.raises(ValueError, match="not valid JSON"):
        faults.load_plan(str(p))


def test_plan_fires_deterministically():
    """Same seed -> the same hits fire, on every run."""
    def fire_pattern(seed):
        plan = faults.FaultPlan(
            [_rule(point="serve.score", kind="transient", p=0.5)],
            seed=seed)
        pattern = []
        with faults.active(plan):
            for _ in range(32):
                try:
                    faults.point("serve.score")
                    pattern.append(0)
                except faults.TransientIOError:
                    pattern.append(1)
        return pattern

    a, b = fire_pattern(7), fire_pattern(7)
    assert a == b
    assert 0 < sum(a) < 32  # p=0.5 actually mixes outcomes
    assert fire_pattern(8) != a  # and the seed matters


def test_point_is_noop_without_plan_and_rejects_typos():
    assert faults.point("serve.score") is None
    assert faults.point("ingest.write_shard", payload=b"x") == b"x"
    plan = faults.FaultPlan([])
    with faults.active(plan):
        with pytest.raises(ValueError, match="unregistered"):
            faults.point("serve.scoore")


def test_at_hit_and_max_hits_semantics():
    plan = faults.FaultPlan([
        _rule(point="cascade.round", kind="transient", at_hit=3),
        _rule(point="stream.read_shard", kind="transient", max_hits=2),
    ])
    with faults.active(plan):
        outcomes = []
        for _ in range(5):
            try:
                faults.point("cascade.round")
                outcomes.append("ok")
            except faults.TransientIOError:
                outcomes.append("fault")
        assert outcomes == ["ok", "ok", "fault", "ok", "ok"]
        reads = []
        for _ in range(4):
            try:
                faults.point("stream.read_shard")
                reads.append("ok")
            except faults.TransientIOError:
                reads.append("fault")
        assert reads == ["fault", "fault", "ok", "ok"]


# ----------------------------------------------------------------- retry
def test_retry_backoff_schedule_is_deterministic_and_bounded():
    sleeps = []
    pol = faults.RetryPolicy(max_attempts=5, base_delay_s=0.01,
                             max_delay_s=0.03, multiplier=2.0, jitter=0.5,
                             seed=3)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 5:
            raise faults.TransientIOError("flaky")
        return "done"

    r = faults.Retry(pol, op="t", metrics=MetricsRegistry(),
                     sleep=sleeps.append)
    assert r(flaky) == "done"
    assert len(sleeps) == 4
    # deterministic: a second instance reproduces the exact schedule
    sleeps2 = []
    calls["n"] = 0
    faults.Retry(pol, op="t", metrics=MetricsRegistry(),
                 sleep=sleeps2.append)(flaky)
    assert sleeps == sleeps2
    # bounded by max_delay * (1 + jitter), growing from base * (1 - jitter)
    assert all(0.005 <= s <= 0.045 for s in sleeps)


def test_retry_exhaustion_and_classification():
    reg = MetricsRegistry()
    r = faults.Retry(faults.RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                        jitter=0.0),
                     op="x", metrics=reg, sleep=lambda s: None)

    def always():
        raise faults.TransientIOError("nope")

    with pytest.raises(faults.RetryExhaustedError) as ei:
        r(always)
    assert ei.value.attempts == 3
    assert isinstance(ei.value.last, faults.TransientIOError)
    assert reg.counter("retry.exhausted", op="x").value == 1

    # non-retryable errors propagate immediately, attempt 1
    def broken():
        raise KeyError("real bug")

    with pytest.raises(KeyError):
        r(broken)

    # SimulatedKill is BaseException: never retried, never wrapped
    def killed():
        raise faults.SimulatedKill("die")

    with pytest.raises(faults.SimulatedKill):
        r(killed)


# --------------------------------------------------------------- breaker
def test_breaker_trip_halfopen_recover_and_reopen():
    clock = {"t": 0.0}
    events = []
    br = faults.CircuitBreaker(threshold=3, cooldown_s=10.0, name="m",
                               clock=lambda: clock["t"],
                               listener=events.append)
    assert br.state == "closed" and br.allow()
    for _ in range(2):
        br.record_failure()
    assert br.state == "closed"  # 2 < threshold
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"  # success reset the consecutive count
    br.record_failure()
    assert br.state == "open" and events == ["tripped"]
    assert not br.allow()  # open: fail fast

    clock["t"] = 10.0  # cooldown elapsed -> half-open admits ONE probe
    assert br.state == "half_open"
    assert br.allow()
    assert not br.allow()  # only one probe outstanding
    br.record_failure()  # probe failed -> reopen, fresh cooldown
    assert br.state == "open" and not br.allow()

    clock["t"] = 20.0
    assert br.allow()
    br.record_success()  # probe succeeded -> closed
    assert br.state == "closed" and br.allow()
    assert br.trips == 1 and br.recoveries == 1
    assert "recovered" in events and "reopened" in events
    d = br.describe()
    assert d["state"] == "closed" and d["trips"] == 1


# ----------------------------------------------------- stream under chaos
def _mk_dataset(tmp_path, n=301, rows_per_shard=64):
    from tpusvm.stream import ingest_arrays, open_dataset

    X, Y = rings(n=n, seed=11)
    out = str(tmp_path / "ds")
    ingest_arrays(out, X, Y, rows_per_shard=rows_per_shard)
    return X, Y, open_dataset(out)


def test_reader_retries_transient_faults_to_parity(tmp_path):
    from tpusvm.stream import ShardReader

    X, Y, ds = _mk_dataset(tmp_path)
    reg = MetricsRegistry()
    plan = faults.FaultPlan(
        [_rule(point="stream.read_shard", kind="transient", max_hits=3)],
        seed=5)
    with faults.active(plan):
        blocks = list(ShardReader(ds, metrics=reg))
    assert np.array_equal(np.concatenate([b[0] for b in blocks]), X)
    assert reg.counter("retry.recovered", op="stream.read_shard").value >= 1
    assert reg.counter("retry.exhausted", op="stream.read_shard").value == 0


def test_reader_exhausted_retries_name_the_shard(tmp_path):
    from tpusvm.stream import ShardError, ShardReader

    _, _, ds = _mk_dataset(tmp_path)
    # more consecutive faults than the default 4-attempt budget
    plan = faults.FaultPlan(
        [_rule(point="stream.read_shard", kind="transient", max_hits=50)],
        seed=5)
    with faults.active(plan):
        with pytest.raises(ShardError, match="READ_FAILED") as ei:
            list(ShardReader(ds, metrics=MetricsRegistry()))
    assert ei.value.status == StreamStatus.READ_FAILED
    assert ei.value.filename.startswith("shard-")


def test_corrupted_shard_is_named_not_a_zlib_traceback(tmp_path):
    """Satellite: a bit-flipped shard surfaces as ShardError naming the
    shard (with the StreamStatus), from load_shard and from the prefetch
    thread alike; validate() classifies it CHECKSUM_MISMATCH."""
    from tpusvm.stream import ShardError, ShardReader, open_dataset

    _, _, ds = _mk_dataset(tmp_path)
    # truncate one shard mid-file: np.load dies inside zlib/zipfile
    victim = ds.shard_path(2)
    raw = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(raw[: len(raw) // 2])
    ds2 = open_dataset(str(tmp_path / "ds"))
    statuses = ds2.validate()
    assert statuses[2] == StreamStatus.CHECKSUM_MISMATCH
    with pytest.raises(ShardError, match="shard-00002") as ei:
        ds2.load_shard(2)
    assert ei.value.status == StreamStatus.CHECKSUM_MISMATCH
    with pytest.raises(ShardError, match="shard-00002"):
        list(ShardReader(ds2, metrics=MetricsRegistry()))


def test_info_cli_reports_corrupt_shard_instead_of_tracebacking(
        tmp_path, capsys):
    from tpusvm.cli import main

    _, _, ds = _mk_dataset(tmp_path)
    victim = ds.shard_path(1)
    raw = open(victim, "rb").read()
    with open(victim, "wb") as f:
        f.write(raw[: len(raw) // 2])
    rc = main(["info", str(tmp_path / "ds")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "shard-00001.npz: CHECKSUM_MISMATCH" in out


def test_ingest_kill_then_journal_resume_is_identical(tmp_path):
    """Satellite + tentpole: a killed ingest leaves a journal (and NO
    manifest, NO torn shard file); resume completes to a dataset
    bit-identical to an uninterrupted ingest."""
    from tpusvm.stream import ingest_blocks, open_dataset

    X, Y = rings(n=301, seed=11)

    def blocks():
        for s in range(0, len(X), 50):
            yield X[s: s + 50], Y[s: s + 50]

    ref = ingest_blocks(str(tmp_path / "ref"), blocks(), rows_per_shard=64)
    out = str(tmp_path / "crashy")
    plan = faults.FaultPlan(
        [_rule(point="ingest.write_shard", kind="kill", at_hit=3)])
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            ingest_blocks(out, blocks(), rows_per_shard=64)
    assert os.path.exists(os.path.join(out, "ingest.journal.json"))
    assert not os.path.exists(os.path.join(out, "manifest.json"))
    assert not any(f.endswith(".tmp") for f in os.listdir(out))

    m = ingest_blocks(out, blocks(), rows_per_shard=64, resume=True)
    assert [s.sha256 for s in m.shards] == [s.sha256 for s in ref.shards]
    ds = open_dataset(out)
    assert all(s == StreamStatus.OK for s in ds.validate())
    assert not os.path.exists(os.path.join(out, "ingest.journal.json"))


def test_ingest_write_transients_are_retried_to_success(tmp_path):
    from tpusvm.obs.registry import default_registry, reset_default_registry
    from tpusvm.stream import ingest_arrays, open_dataset

    reset_default_registry()
    try:
        X, Y = rings(n=200, seed=1)
        out = str(tmp_path / "t")
        plan = faults.FaultPlan(
            [_rule(point="ingest.write_shard", kind="transient",
                   max_hits=2)])
        with faults.active(plan):
            ingest_arrays(out, X, Y, rows_per_shard=64)
        assert all(s == StreamStatus.OK
                   for s in open_dataset(out).validate())
        reg = default_registry()
        assert reg.counter("retry.recovered",
                           op="ingest.write_shard").value >= 1
    finally:
        reset_default_registry()


def test_ingest_corrupt_write_is_caught_by_validation(tmp_path):
    from tpusvm.stream import ingest_arrays, open_dataset

    X, Y = rings(n=301, seed=11)
    out = str(tmp_path / "c")
    plan = faults.FaultPlan(
        [_rule(point="ingest.write_shard", kind="corrupt", at_hit=2)],
        seed=9)
    with faults.active(plan):
        ingest_arrays(out, X, Y, rows_per_shard=64)
    statuses = open_dataset(out).validate()
    assert statuses[1] == StreamStatus.CHECKSUM_MISMATCH
    assert all(s == StreamStatus.OK
               for i, s in enumerate(statuses) if i != 1)


def test_ingest_resume_refuses_changed_settings(tmp_path):
    from tpusvm.stream import ingest_blocks

    X, Y = rings(n=200, seed=1)
    out = str(tmp_path / "j")
    plan = faults.FaultPlan(
        [_rule(point="ingest.write_shard", kind="kill", at_hit=2)])
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            ingest_blocks(out, [(X, Y)], rows_per_shard=64)
    with pytest.raises(ValueError, match="rows_per_shard"):
        ingest_blocks(out, [(X, Y)], rows_per_shard=32, resume=True)


# ------------------------------------------------- crash-safe training
def _solve_args(n=400, q=16):
    X, Y = rings(n=n, seed=11)
    Xs = jnp.asarray(MinMaxScaler().fit_transform(X), jnp.float32)
    return Xs, jnp.asarray(Y), dict(C=10.0, gamma=10.0, q=q,
                                    accum_dtype=jnp.float64)


def test_checkpointed_solve_bit_identical_to_plain(tmp_path):
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve

    Xs, Y, kw = _solve_args()
    plain = blocked_smo_solve(Xs, Y, **kw)
    assert Status(int(plain.status)) == Status.CONVERGED
    ck = str(tmp_path / "ck.npz")
    res = checkpointed_blocked_solve(Xs, Y, checkpoint_path=ck,
                                     checkpoint_every=4, **kw)
    assert np.asarray(res.alpha).tobytes() == np.asarray(plain.alpha).tobytes()
    assert float(res.b) == float(plain.b)
    assert int(res.n_outer) == int(plain.n_outer)
    assert not os.path.exists(ck)  # completed solve cleans up


def test_kill_at_every_checkpoint_resume_bit_identical(tmp_path):
    """The tentpole gate: for EVERY checkpoint k, a run killed at k and
    resumed reproduces the uninterrupted model bit-for-bit (alpha bytes,
    SV ids, b)."""
    from tpusvm.oracle.smo import get_sv_indices
    from tpusvm.solver.blocked import blocked_smo_solve
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve

    Xs, Y, kw = _solve_args()
    plain = blocked_smo_solve(Xs, Y, **kw)
    ref_alpha = np.asarray(plain.alpha)
    ref_sv = get_sv_indices(ref_alpha, 1e-8)
    n_ckpts = int(plain.n_outer) // 4
    assert n_ckpts >= 2, "problem too easy to exercise checkpoints"

    for k in range(1, n_ckpts + 1):
        ck = str(tmp_path / f"ck{k}.npz")
        plan = faults.FaultPlan(
            [_rule(point="solver.outer_checkpoint", kind="kill",
                   at_hit=k)])
        with pytest.raises(faults.SimulatedKill):
            with faults.active(plan):
                checkpointed_blocked_solve(Xs, Y, checkpoint_path=ck,
                                           checkpoint_every=4, **kw)
        res = checkpointed_blocked_solve(Xs, Y, checkpoint_path=ck,
                                         checkpoint_every=4, resume=True,
                                         **kw)
        a = np.asarray(res.alpha)
        assert a.tobytes() == ref_alpha.tobytes(), f"kill at ckpt {k}"
        assert np.array_equal(get_sv_indices(a, 1e-8), ref_sv)
        assert float(res.b) == float(plain.b)


def test_checkpoint_write_transients_are_retried(tmp_path):
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve

    Xs, Y, kw = _solve_args()
    plan = faults.FaultPlan(
        [_rule(point="solver.outer_checkpoint", kind="transient",
               max_hits=2)])
    with faults.active(plan):
        res = checkpointed_blocked_solve(
            Xs, Y, checkpoint_path=str(tmp_path / "ck.npz"),
            checkpoint_every=4, **kw)
    assert Status(int(res.status)) == Status.CONVERGED


def test_solver_checkpoint_fingerprint_refuses_other_solves(tmp_path):
    from tpusvm.solver.checkpoint import checkpointed_blocked_solve

    Xs, Y, kw = _solve_args()
    ck = str(tmp_path / "ck.npz")
    plan = faults.FaultPlan(
        [_rule(point="solver.outer_checkpoint", kind="kill", at_hit=1)])
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            checkpointed_blocked_solve(Xs, Y, checkpoint_path=ck,
                                       checkpoint_every=2, **kw)
    assert os.path.exists(ck) or True  # kill may precede the first write
    # ensure at least one durable checkpoint to resume against
    if not os.path.exists(ck):
        plan = faults.FaultPlan(
            [_rule(point="solver.outer_checkpoint", kind="kill",
                   at_hit=2)])
        with pytest.raises(faults.SimulatedKill):
            with faults.active(plan):
                checkpointed_blocked_solve(Xs, Y, checkpoint_path=ck,
                                           checkpoint_every=2, **kw)
    assert os.path.exists(ck)
    # a different gamma is a different solve: refused, naming the field
    bad = dict(kw, gamma=20.0)
    with pytest.raises(ValueError, match="gamma"):
        checkpointed_blocked_solve(Xs, Y, checkpoint_path=ck,
                                   checkpoint_every=2, resume=True, **bad)
    # different training bytes: refused too
    with pytest.raises(ValueError, match="crc32"):
        checkpointed_blocked_solve(
            jnp.asarray(np.asarray(Xs) + 1e-3), Y, checkpoint_path=ck,
            checkpoint_every=2, resume=True, **kw)
    # a non-checkpoint npz is refused with a clear error
    np.savez(str(tmp_path / "junk"), a=np.zeros(3))
    with pytest.raises(ValueError, match="not a tpusvm solver checkpoint"):
        checkpointed_blocked_solve(
            Xs, Y, checkpoint_path=str(tmp_path / "junk.npz"),
            checkpoint_every=2, resume=True, **kw)


def test_cli_single_mode_checkpoint_resume(tmp_path, capsys):
    """train --checkpoint/--resume now works beyond cascade mode: a
    killed single-mode run resumes to the same smoke-passing model."""
    from tpusvm.cli import main

    ck = str(tmp_path / "ck.npz")
    plan_path = str(tmp_path / "kill.json")
    with open(plan_path, "w") as f:
        json.dump({"format_version": 1, "rules": [
            {"point": "solver.outer_checkpoint", "kind": "kill",
             "at_hit": 1}]}, f)
    with pytest.raises(faults.SimulatedKill):
        main(["train", "--smoke", "-q", "--checkpoint", ck,
              "--checkpoint-every", "1", "--faults", plan_path])
    faults.deactivate()
    rc = main(["train", "--smoke", "-q", "--checkpoint", ck,
               "--checkpoint-every", "1", "--resume"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "train smoke ok" in out


def test_cli_checkpoint_guards(tmp_path):
    from tpusvm.cli import main

    with pytest.raises(SystemExit, match="blocked solver"):
        main(["train", "--smoke", "-q", "--solver", "pair",
              "--checkpoint", str(tmp_path / "c.npz")])
    with pytest.raises(SystemExit, match="oracle"):
        main(["train", "--synthetic", "rings", "--n", "64", "--mode",
              "oracle", "--checkpoint", str(tmp_path / "c.npz")])


# --------------------------------------------------- degraded-mode serve
@pytest.fixture(scope="module")
def serve_model():
    X, Y = rings(n=240, seed=2)
    return BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                     dtype=jnp.float64).fit(X, Y)


def _server(model, **cfg_kw):
    from tpusvm.serve import ServeConfig, Server

    srv = Server(ServeConfig(max_batch=4, max_delay_ms=0.5, **cfg_kw),
                 dtype=jnp.float64)
    srv.add_model("m", model)
    srv.warmup()
    return srv


def test_serve_transient_scoring_faults_are_retried(serve_model):
    Xq, _ = rings(n=8, seed=3)
    plan = faults.FaultPlan(
        [_rule(point="serve.score", kind="transient", max_hits=2)])
    with _server(serve_model, score_retries=3) as srv:
        ref = srv.predict_direct("m", Xq)[0]
        with faults.active(plan):
            res = srv.submit_many("m", Xq)
        assert all(r.ok for r in res)
        np.testing.assert_array_equal(np.array([r.scores for r in res]),
                                      ref)
        snap = srv.metrics("m")
        assert snap["retries"] >= 1 and snap["errors"] == 0
        assert snap["breaker_trips"] == 0


def test_serve_breaker_trips_sheds_fast_and_recovers(serve_model, tmp_path):
    """Acceptance (c): persistent scoring failures trip the per-model
    breaker; further requests fail fast with UNAVAILABLE (no deadlock, no
    kernel time); after the cooldown a half-open probe recovers; and the
    whole lifecycle is visible in an obs trace + counters."""
    from tpusvm.obs import Tracer, read_trace

    trace_path = str(tmp_path / "chaos.jsonl")
    tracer = Tracer(trace_path)
    faults.set_event_sink(tracer.event)
    Xq, _ = rings(n=4, seed=3)
    # enough fault budget to exhaust per-request retries (1 attempt each,
    # score_retries=0) and trip the threshold=2 breaker
    plan = faults.FaultPlan(
        [_rule(point="serve.score", kind="transient", max_hits=2)])
    with _server(serve_model, score_retries=0, breaker_threshold=2,
                 breaker_cooldown_s=0.3) as srv:
        with faults.active(plan):
            r1 = srv.submit("m", Xq[0])
            r2 = srv.submit("m", Xq[1])
            assert r1.status == ServeStatus.ERROR
            assert r2.status == ServeStatus.ERROR
            assert srv.health()["status"] == "degraded"
            assert srv.health()["models"]["m"] == "open"
            # breaker open: fast UNAVAILABLE, and far quicker than a
            # scoring attempt + timeout would be
            t0 = time.monotonic()
            r3 = srv.submit("m", Xq[2])
            assert r3.status == ServeStatus.UNAVAILABLE
            assert time.monotonic() - t0 < 0.5
            snap = srv.metrics("m")
            assert snap["breaker_trips"] == 1
            assert snap["unavailable"] >= 1
            # cooldown elapses; the fault budget (max_hits=2) is spent,
            # so the half-open probe scores cleanly and the breaker closes
            time.sleep(0.35)
            r4 = srv.submit("m", Xq[3])
            assert r4.ok
            assert srv.health()["models"]["m"] == "closed"
            assert srv.metrics("m")["breaker_recoveries"] == 1
    tracer.close()
    names = {r["name"] for r in read_trace(trace_path)
             if r["kind"] == "event"}
    assert "fault.injected" in names
    assert "breaker.tripped" in names
    assert "breaker.recovered" in names


def test_microbatcher_sheds_overloaded_beyond_threshold():
    from tpusvm.serve import Metrics, MicroBatcher

    metrics = Metrics(buckets=(1,))
    release = threading.Event()

    def slow(X):
        release.wait(2.0)
        return np.zeros(X.shape[0]), np.ones(X.shape[0], np.int32)

    b = MicroBatcher(slow, max_batch=1, max_delay_s=0.0, queue_size=8,
                     timeout_s=5.0, metrics=metrics, shed_at=2)
    try:
        results, threads = [], []
        lock = threading.Lock()

        def fire():
            r = b.submit(np.zeros(2))
            with lock:
                results.append(r.status)

        t = threading.Thread(target=fire)
        t.start()
        threads.append(t)
        time.sleep(0.05)  # worker is now blocked inside slow()
        # fill to the shed threshold, then beyond it
        for _ in range(6):
            th = threading.Thread(target=fire)
            th.start()
            threads.append(th)
            time.sleep(0.01)
        release.set()
        for th in threads:
            th.join(3.0)
        assert ServeStatus.OVERLOADED in results
        assert metrics.snapshot()["overloaded"] >= 1
        # shed requests never entered the queue; the accepted ones scored
        assert results.count(ServeStatus.OK) >= 1
    finally:
        release.set()
        b.close()


def test_server_drain_completes_inflight_then_refuses(serve_model):
    Xq, _ = rings(n=8, seed=4)
    with _server(serve_model) as srv:
        inflight = []
        t = threading.Thread(
            target=lambda: inflight.extend(srv.submit_many("m", Xq)))
        t.start()
        assert srv.drain(timeout_s=5.0)
        t.join(5.0)
        # everything accepted before/through the drain resolved cleanly
        assert all(r.status in (ServeStatus.OK, ServeStatus.DRAINING)
                   for r in inflight)
        assert any(r.ok for r in inflight)
        r = srv.submit("m", Xq[0])
        assert r.status == ServeStatus.DRAINING
        assert srv.health()["status"] == "draining"
        assert srv.status()["draining"] is True


def test_http_healthz_drain_and_degraded_codes(serve_model):
    import urllib.error
    import urllib.request

    from tpusvm.serve.http import make_http_server, start_http_thread

    Xq, _ = rings(n=4, seed=5)
    with _server(serve_model) as srv:
        httpd = make_http_server(srv, port=0)
        start_http_thread(httpd)
        try:
            port = httpd.server_address[1]
            base = f"http://127.0.0.1:{port}"
            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz").read())
            assert health["status"] == "ok"
            assert health["models"] == {"m": "closed"}

            # drain over HTTP; healthz then reports 503 + draining, and
            # predict requests come back DRAINING with a 503
            resp = json.loads(urllib.request.urlopen(
                urllib.request.Request(f"{base}/admin/drain", data=b"",
                                       method="POST")).read())
            assert resp == {"drained": True}
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["status"] == "draining"
            body = json.dumps({"instances": Xq.tolist()}).encode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/models/m:predict", data=body,
                    headers={"Content-Type": "application/json"}))
            assert ei.value.code == 503
            assert (json.loads(ei.value.read())["statuses"]
                    == ["DRAINING"] * 4)
        finally:
            httpd.shutdown()


# ---------------------------------------------- cascade resume satellites
def test_cascade_resume_refuses_other_partition_or_topology(tmp_path):
    """Satellite: a checkpoint from a different cascade config is
    rejected with a specific config error BEFORE any compile — not a
    shape crash mid-run. (Runs even where jax lacks shard_map: the check
    fires before the round function is built.)"""
    from tpusvm.config import CascadeConfig
    from tpusvm.parallel.cascade import cascade_fit, save_round_state
    from tpusvm.parallel.svbuffer import empty

    X, Y = rings(n=128, seed=3)
    Xs = MinMaxScaler().fit_transform(X)
    ck = str(tmp_path / "cascade.npz")
    buf = empty(64, Xs.shape[1])
    save_round_state(ck, buf, {1, 2}, rnd=2, b=0.5, n_shards=4,
                     topology="star")

    cfg = SVMConfig(C=10.0, gamma=10.0)
    with pytest.raises(ValueError, match="n_shards=4"):
        cascade_fit(Xs, Y, cfg,
                    CascadeConfig(n_shards=8, sv_capacity=64,
                                  topology="star"),
                    checkpoint_path=ck, resume=True)
    with pytest.raises(ValueError, match="topology='star'"):
        cascade_fit(Xs, Y, cfg,
                    CascadeConfig(n_shards=4, sv_capacity=64,
                                  topology="tree"),
                    checkpoint_path=ck, resume=True)
    # shape mismatches still raise their specific error (pre-compile too)
    with pytest.raises(ValueError, match="checkpoint shapes"):
        cascade_fit(Xs, Y, cfg,
                    CascadeConfig(n_shards=4, sv_capacity=32,
                                  topology="star"),
                    checkpoint_path=ck, resume=True)


def test_cascade_round_is_an_injection_point(tmp_path):
    """A kill rule at cascade.round dies before any device work — the
    checkpoint (if any) is what survives, same as a real mid-run death."""
    import jax

    if not hasattr(jax, "shard_map"):
        pytest.skip("installed jax lacks jax.shard_map (cascade untestable "
                    "on this environment)")
    from tpusvm.config import CascadeConfig
    from tpusvm.parallel.cascade import cascade_fit

    X, Y = rings(n=128, seed=3)
    Xs = MinMaxScaler().fit_transform(X)
    plan = faults.FaultPlan(
        [_rule(point="cascade.round", kind="kill", at_hit=1)])
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            cascade_fit(Xs, Y, SVMConfig(C=10.0, gamma=10.0),
                        CascadeConfig(n_shards=4, sv_capacity=64,
                                      topology="star"))


# ------------------------------------------------------------- reporting
def test_fault_counters_reach_the_default_registry(tmp_path):
    from tpusvm.obs.registry import default_registry, reset_default_registry
    from tpusvm.stream import ShardReader

    reset_default_registry()
    try:
        _, _, ds = _mk_dataset(tmp_path, n=150)
        plan = faults.FaultPlan(
            [_rule(point="stream.read_shard", kind="transient",
                   max_hits=1)])
        with faults.active(plan):
            list(ShardReader(ds))
        snap = default_registry().snapshot()
        by_key = {(e["name"], tuple(sorted(e["labels"].items()))):
                  e["value"] for e in snap["metrics"]}
        assert by_key[("faults.injected",
                       (("kind", "transient"),
                        ("point", "stream.read_shard")))] == 1
        assert by_key[("retry.recovered",
                       (("op", "stream.read_shard"),))] == 1
    finally:
        reset_default_registry()
