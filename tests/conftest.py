"""Test harness config: simulated 8-device CPU mesh + float64.

Multi-chip logic is tested without a pod via XLA's host-platform device
simulation (SURVEY.md §4 "Consequences"): 8 virtual CPU devices exercise the
same shard_map/collective code paths as a real TPU mesh. float64 is enabled
so the JAX solver can be compared against the float64 NumPy oracle at
tight tolerances.
"""

import os

# Must run before jax initialises its backends.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
