"""Test harness config: simulated 8-device CPU mesh + float64.

Multi-chip logic is tested without a pod via XLA's host-platform device
simulation (SURVEY.md §4 "Consequences"): 8 virtual CPU devices exercise the
same shard_map/collective code paths as a real TPU mesh. float64 is enabled
so the JAX solver can be compared against the float64 NumPy oracle at tight
tolerances.

Note: this environment's sitecustomize registers the experimental `axon` TPU
platform at interpreter startup and programmatically sets jax_platforms, so
an env-var JAX_PLATFORMS=cpu is ignored; the jax.config.update below is what
actually selects CPU (backends are not yet initialised at conftest time).

TPU-gated tests (tests/test_pallas_tpu.py): run with
TPUSVM_TEST_PLATFORM=native to keep the real backend instead of forcing
CPU — those tests skip themselves when the backend is not a TPU.
"""

import os

# Must be set before the CPU backend initialises.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

if os.environ.get("TPUSVM_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_report_header(config):
    return f"jax devices: {jax.devices()}"
