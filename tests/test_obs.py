"""Tests for tpusvm.obs — the unified telemetry subsystem (ISSUE 5).

Contracts:
  * trace JSONL schema roundtrip (deterministic via injected clock),
    nested-span parentage, version gate;
  * registry snapshot merge is exact, associative and commutative on
    counters/gauges/histograms;
  * the solver's convergence ring wraps correctly and is BIT-transparent
    (same SV ids / b / accuracy / status with telemetry on or off);
  * PhaseTimer keeps the reference's three-line report contract while
    emitting spans;
  * serve metrics output stays parseable/identical in shape after the
    registry migration (test_serve.py holds the value-level parity
    test; here the registry view itself is checked);
  * the `tpusvm report` CLI renders a trace and its --smoke gate works.
"""

import json

import numpy as np
import pytest

from tpusvm.obs import (
    MetricsRegistry,
    PhaseTimer,
    Tracer,
    merge_snapshots,
    read_trace,
)
from tpusvm.obs.convergence import ConvergenceTelemetry, materialize


class FakeClock:
    """Deterministic monotonic clock for bit-stable trace files."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


# ------------------------------------------------------------------ trace
def test_trace_roundtrip_schema(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with Tracer(path, clock=FakeClock(), wall=lambda: 0.0,
                argv=["train"]) as tr:
        with tr.span("data", phase=True):
            pass
        with tr.span("training", phase=True):
            tr.event("convergence.round", round=1, gap=0.5, updates=3,
                     status="RUNNING")
    records = read_trace(path)
    kinds = [r["kind"] for r in records]
    assert kinds == ["meta", "span", "event", "span", "end"]
    assert all(r["v"] == 1 for r in records)
    assert records[0]["argv"] == ["train"]
    spans = {r["name"]: r for r in records if r["kind"] == "span"}
    assert spans["data"]["dur_s"] > 0
    assert spans["data"]["attrs"] == {"phase": True}
    # the event is parented to the span that was open when it fired
    ev = next(r for r in records if r["kind"] == "event")
    assert ev["parent"] == spans["training"]["id"]
    assert records[-1]["total_s"] > 0
    # deterministic clock => re-running produces the identical file
    path2 = str(tmp_path / "t2.jsonl")
    with Tracer(path2, clock=FakeClock(), wall=lambda: 0.0,
                argv=["train"]) as tr:
        with tr.span("data", phase=True):
            pass
        with tr.span("training", phase=True):
            tr.event("convergence.round", round=1, gap=0.5, updates=3,
                     status="RUNNING")
    assert open(path).read() == open(path2).read()


def test_trace_nested_span_parentage(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with Tracer(path, clock=FakeClock()) as tr:
        with tr.span("outer"):
            with tr.span("inner"):
                pass
    spans = {r["name"]: r for r in read_trace(path) if r["kind"] == "span"}
    assert spans["inner"]["parent"] == spans["outer"]["id"]
    assert spans["outer"]["parent"] is None
    # inner closes first => file order is inner, outer; both nest in time
    assert spans["outer"]["t0"] < spans["inner"]["t0"]
    assert spans["inner"]["t1"] < spans["outer"]["t1"]


def test_trace_version_gate(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"v": 99, "kind": "meta"}\n')
    with pytest.raises(ValueError, match="schema version"):
        read_trace(str(p))
    p.write_text("not json\n")
    with pytest.raises(ValueError, match="not a JSON record"):
        read_trace(str(p))


def test_trace_numpy_attrs_jsonable(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with Tracer(path, clock=FakeClock()) as tr:
        tr.event("e", count=np.int64(3), arr=np.arange(2),
                 val=np.float32(0.5))
    ev = next(r for r in read_trace(path) if r["kind"] == "event")
    assert ev["attrs"] == {"count": 3, "arr": [0, 1], "val": 0.5}


# --------------------------------------------------------------- registry
def _make_reg(counter_vals, gauge_val, hist_obs):
    reg = MetricsRegistry()
    for name, v in counter_vals.items():
        reg.counter(name).inc(v)
    reg.counter("labelled", model="m").inc(2)
    reg.gauge("depth").set_max(gauge_val)
    h = reg.histogram("lat", bounds=(0.1, 1.0))
    for v in hist_obs:
        h.observe(v)
    return reg


def test_registry_snapshot_shape_and_text():
    reg = _make_reg({"reqs": 3}, 5, [0.05, 0.5, 2.0])
    snap = reg.snapshot()
    assert snap["v"] == 1
    json.dumps(snap)  # JSON-able end to end
    by_name = {(e["name"], tuple(sorted(e["labels"].items()))): e
               for e in snap["metrics"]}
    assert by_name[("reqs", ())]["value"] == 3
    assert by_name[("labelled", (("model", "m"),))]["value"] == 2
    assert by_name[("depth", ())]["value"] == 5
    h = by_name[("lat", ())]
    assert h["counts"] == [1, 1, 1] and h["count"] == 3
    text = reg.render_text()
    assert "tpusvm_reqs_total 3" in text
    assert 'tpusvm_labelled_total{model="m"} 2' in text
    assert 'le="+Inf"} 3' in text
    assert "tpusvm_lat_count 3" in text


def test_registry_merge_commutative_associative():
    a = _make_reg({"reqs": 3, "only_a": 1}, 5, [0.05]).snapshot()
    b = _make_reg({"reqs": 4}, 2, [0.5, 2.0]).snapshot()
    c = _make_reg({"reqs": 10, "only_c": 7}, 9, []).snapshot()
    ab = merge_snapshots(a, b)
    ba = merge_snapshots(b, a)
    assert ab == ba  # commutative
    assert merge_snapshots(ab, c) == merge_snapshots(
        a, merge_snapshots(b, c))  # associative
    by_name = {e["name"]: e for e in ab["metrics"] if not e["labels"]}
    assert by_name["reqs"]["value"] == 7          # counters add
    assert by_name["depth"]["value"] == 5         # gauges max
    assert by_name["lat"]["counts"] == [1, 1, 1]  # histograms add
    assert by_name["lat"]["count"] == 3
    assert by_name["only_a"]["value"] == 1        # disjoint keys survive


def test_registry_merge_rejects_mismatched_bounds():
    a = MetricsRegistry()
    a.histogram("h", bounds=(1.0,)).observe(0.5)
    b = MetricsRegistry()
    b.histogram("h", bounds=(2.0,)).observe(0.5)
    with pytest.raises(ValueError, match="bounds"):
        merge_snapshots(a.snapshot(), b.snapshot())


def test_registry_rejects_version_and_type_clash():
    reg = MetricsRegistry()
    reg.gauge("y")
    with pytest.raises(TypeError, match="already registered"):
        reg.counter("y")  # same name, different type
    with pytest.raises(ValueError, match="snapshot version"):
        merge_snapshots({"v": 99, "metrics": []})


# ------------------------------------------------------------ convergence
def test_convergence_ring_no_wrap():
    tele = ConvergenceTelemetry(
        gap=np.array([0.5, 0.1, np.nan, np.nan]),
        n_upd=np.array([7, 2, 0, 0], np.int32),
        status=np.array([0, 1, 0, 0], np.int32),
        count=np.int32(2),
    )
    conv = materialize(tele)
    assert not conv["wrapped"] and conv["rounds_recorded"] == 2
    np.testing.assert_array_equal(conv["gap"], [0.5, 0.1])
    np.testing.assert_array_equal(conv["updates"], [7, 2])


def test_convergence_ring_wraparound():
    # 6 rounds through a 4-slot ring: slots hold rounds [4,5,2,3] and
    # the unwrap must return [2,3,4,5] (oldest surviving first)
    tele = ConvergenceTelemetry(
        gap=np.array([4.0, 5.0, 2.0, 3.0]),
        n_upd=np.array([40, 50, 20, 30], np.int32),
        status=np.array([4, 5, 2, 3], np.int32),
        count=np.int32(6),
    )
    conv = materialize(tele)
    assert conv["wrapped"] and conv["rounds_recorded"] == 6
    np.testing.assert_array_equal(conv["gap"], [2.0, 3.0, 4.0, 5.0])
    np.testing.assert_array_equal(conv["updates"], [20, 30, 40, 50])
    np.testing.assert_array_equal(conv["status"], [2, 3, 4, 5])


def _solve_rings(telemetry):
    import jax.numpy as jnp

    from tpusvm.data import MinMaxScaler, rings
    from tpusvm.solver.blocked import blocked_smo_solve

    X, Y = rings(n=300, seed=0)
    Xs = MinMaxScaler().fit(X).transform(X)
    return blocked_smo_solve(
        jnp.asarray(Xs, jnp.float32), jnp.asarray(Y),
        C=10.0, gamma=10.0, q=64, max_inner=128,
        accum_dtype=jnp.float64, telemetry=telemetry,
    )


def test_solver_telemetry_bit_transparent():
    """The acceptance-criteria identity: telemetry on/off gives the same
    alpha BYTES (hence the same SV ids, b, accuracy) and statuses."""
    r0 = _solve_rings(0)
    r1 = _solve_rings(16)
    assert r0.telemetry is None
    assert np.array_equal(np.asarray(r0.alpha), np.asarray(r1.alpha))
    assert float(r0.b) == float(r1.b)
    assert int(r0.status) == int(r1.status)
    assert int(r0.n_iter) == int(r1.n_iter)
    sv0 = np.nonzero(np.asarray(r0.alpha) > 1e-8)[0]
    sv1 = np.nonzero(np.asarray(r1.alpha) > 1e-8)[0]
    np.testing.assert_array_equal(sv0, sv1)


def test_solver_telemetry_records_gap_collapse():
    from tpusvm.status import Status

    res = _solve_rings(16)
    conv = materialize(res.telemetry)
    # every outer-loop body execution records once (incl. the terminal)
    assert conv["rounds_recorded"] == int(res.n_outer) + 1
    assert Status(int(conv["status"][-1])) == Status.CONVERGED
    # the recorded trajectory ends at the stopping criterion
    assert conv["gap"][-1] <= 2.0 * 1e-5 * (1 + 1e-9)
    assert conv["gap"][0] > conv["gap"][-1]
    # updates are conserved: ring total == solver total (no wrap here)
    assert not conv["wrapped"]
    assert conv["updates"].sum() == int(res.n_iter) - 1


def test_solver_telemetry_ring_wraps_on_device():
    res = _solve_rings(2)  # tiny ring, > 2 outer rounds on this problem
    conv = materialize(res.telemetry)
    assert conv["wrapped"]
    assert len(conv["gap"]) == 2
    assert conv["rounds_recorded"] == int(res.n_outer) + 1


def test_binary_svc_surfaces_convergence():
    import jax.numpy as jnp

    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.config import SVMConfig

    X, Y = rings(n=240, seed=1)
    cfg = SVMConfig(C=10.0, gamma=10.0)
    m0 = BinarySVC(config=cfg, solver_opts={"q": 64}).fit(X, Y)
    assert m0.convergence_ is None
    m1 = BinarySVC(config=cfg,
                   solver_opts={"q": 64, "telemetry": 32}).fit(X, Y)
    assert m1.convergence_ is not None
    np.testing.assert_array_equal(m0.sv_ids_, m1.sv_ids_)
    assert m0.b_ == m1.b_
    assert jnp is not None  # keep the import local-style consistent


# -------------------------------------------------------------- PhaseTimer
def test_phase_timer_is_span_adapter(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tracer = Tracer(path, clock=FakeClock())
    t = PhaseTimer(tracer=tracer)
    with t.phase("training"):
        pass
    with t.phase("training"):
        pass
    with t.phase("prediction"):
        pass
    tracer.close()
    # the report contract is unchanged (reference three-line block)
    rep = t.report()
    assert rep.splitlines()[0].startswith("training time: ")
    assert rep.splitlines()[-1].startswith("elapsed time: ")
    # and the same phases landed as spans in the trace
    records = read_trace(path)
    spans = [r for r in records if r["kind"] == "span"
             and r["attrs"].get("phase")]
    assert [s["name"] for s in spans] == ["training", "training",
                                         "prediction"]
    from tpusvm.obs.report import phase_summary

    acc, total = phase_summary(records)
    assert list(acc) == ["training", "prediction"]
    assert total > 0


def test_phase_report_render_single_path():
    """cli/bench/report all render through obs.report.render_phase_lines;
    the contract is pinned here once."""
    from tpusvm.obs.report import render_phase_lines

    out = render_phase_lines({"training": 1.25, "prediction": 0.5}, 2.0)
    assert out == ("training time: 1.250 s\n"
                   "prediction time: 0.500 s\n"
                   "elapsed time: 2.000 s")


# ----------------------------------------------------- serve migration
def test_serve_metrics_registry_view():
    """After the registry migration the serve Metrics exposes a mergeable
    registry snapshot alongside its legacy dict (value parity with the
    legacy surface is pinned by test_serve.py)."""
    from tpusvm.serve.metrics import Metrics

    m = Metrics(buckets=(1, 2, 4))
    m.inc("requests", 3)
    m.observe_batch(2, 2)
    snap = m.registry_snapshot()
    by = {(e["name"], tuple(sorted(e["labels"].items()))): e
          for e in snap["metrics"]}
    assert by[("serve.requests", ())]["value"] == 3
    assert by[("serve.batches", ())]["value"] == 1
    assert by[("serve.bucket_rows", (("bucket", "2"),))]["value"] == 2
    # two servers' snapshots merge exactly
    m2 = Metrics(buckets=(1, 2, 4))
    m2.inc("requests", 4)
    merged = merge_snapshots(snap, m2.registry_snapshot())
    by2 = {(e["name"], tuple(sorted(e["labels"].items()))): e
           for e in merged["metrics"]}
    assert by2[("serve.requests", ())]["value"] == 7


# ---------------------------------------------------------- stream counters
def test_stream_reader_counters(tmp_path):
    from tpusvm.data import rings
    from tpusvm.obs.registry import MetricsRegistry
    from tpusvm.stream import ShardReader, ingest_arrays, open_dataset

    X, Y = rings(n=301, seed=11)
    ingest_arrays(str(tmp_path), X, Y, rows_per_shard=64)
    reg = MetricsRegistry()
    reader = ShardReader(open_dataset(str(tmp_path)), prefetch_depth=2,
                         metrics=reg)
    blocks = list(reader)
    assert len(blocks) == 5
    by = {e["name"]: e for e in reg.snapshot()["metrics"]}
    assert by["stream.shards_loaded"]["value"] == 5
    assert by["stream.live_shards"]["value"] == reader.max_live_shards
    assert by["stream.live_shards"]["value"] <= 3  # depth + 1 bound


# ------------------------------------------------------------- report CLI
def _write_demo_trace(path):
    with Tracer(path, clock=FakeClock()) as tr:
        with tr.span("training", phase=True):
            tr.event("convergence.round", round=1, gap=2.0, updates=100,
                     status="RUNNING")
            tr.event("convergence.round", round=2, gap=1e-5, updates=0,
                     status="CONVERGED")
        reg = MetricsRegistry()
        reg.counter("stream.shards_loaded").inc(5)
        tr.metrics_snapshot(reg.snapshot())


def test_report_cli_renders_trace(tmp_path, capsys):
    from tpusvm.cli import main

    path = str(tmp_path / "t.jsonl")
    _write_demo_trace(path)
    rc = main(["report", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "convergence (b_low - b_high per outer round):" in out
    assert "CONVERGED" in out
    assert "training time: " in out and "elapsed time: " in out
    assert "stream.shards_loaded 5" in out


def test_report_cli_smoke_gates(tmp_path, capsys):
    from tpusvm.cli import main

    good = str(tmp_path / "good.jsonl")
    _write_demo_trace(good)
    assert main(["report", good, "--smoke"]) == 0
    capsys.readouterr()

    # a trace with no convergence records fails the smoke gate
    bare = str(tmp_path / "bare.jsonl")
    with Tracer(bare, clock=FakeClock()) as tr:
        with tr.span("training", phase=True):
            pass
    assert main(["report", bare, "--smoke"]) == 1
    assert "REPORT SMOKE FAILED" in capsys.readouterr().out

    # unreadable schema fails rather than half-rendering
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"v": 99}\n')
    assert main(["report", str(bad), "--smoke"]) == 1
    capsys.readouterr()


def test_train_trace_then_report_roundtrip(tmp_path, capsys):
    """The CI gate, in-process: train --smoke --trace writes a trace the
    report --smoke gate accepts."""
    from tpusvm.cli import main

    path = str(tmp_path / "t.jsonl")
    rc = main(["train", "--smoke", "--trace", path, "-q"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "train smoke ok" in out
    rc = main(["report", path, "--smoke"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "report smoke ok" in out
    # the trace carries the training phase and a converged final round
    records = read_trace(path)
    from tpusvm.obs.report import convergence_rows, phase_summary

    acc, _ = phase_summary(records)
    assert "training" in acc
    conv = convergence_rows(records)
    assert conv[-1]["status"] == "CONVERGED"
    assert conv[-1]["gap"] <= 2e-5 * (1 + 1e-9)


def test_cli_convergence_flag_requires_blocked_single():
    from tpusvm.cli import main

    with pytest.raises(SystemExit, match="blocked"):
        main(["train", "--synthetic", "rings", "--n", "64",
              "--mode", "cascade", "--convergence", "8"])
    with pytest.raises(SystemExit, match="blocked"):
        main(["train", "--synthetic", "rings", "--n", "64",
              "--solver", "pair", "--convergence", "8"])
    with pytest.raises(SystemExit, match="same knob"):
        main(["train", "--synthetic", "rings", "--n", "64",
              "--convergence", "8", "--solver-opt", "telemetry=8"])


def test_cascade_trace_events(tmp_path, capsys):
    """Cascade rounds land in the trace (per-round SV counts and merge
    sizes) through the CLI --trace plumbing."""
    import jax

    if not hasattr(jax, "shard_map"):
        pytest.skip("installed jax lacks jax.shard_map (cascade "
                    "untestable here, same as test_cascade)")
    from tpusvm.cli import main

    path = str(tmp_path / "c.jsonl")
    rc = main([
        "train", "--synthetic", "rings", "--n", "160", "--n-test", "0",
        "--mode", "cascade", "--topology", "star", "--shards", "4",
        "--sv-capacity", "128", "--C", "10", "--gamma", "10",
        "--trace", path, "-q",
    ])
    capsys.readouterr()
    if rc != 0:
        pytest.skip("cascade path unavailable on this jax build")
    records = read_trace(path)
    rounds = [r for r in records if r["kind"] == "event"
              and r["name"] == "cascade.round"]
    assert rounds
    a = rounds[0]["attrs"]
    assert a["sv_count"] > 0
    assert a["topology"] == "star"
    assert len(a["merged_count"]) == 2  # star: layer-1 + layer-2 rows
    spans = [r["name"] for r in records if r["kind"] == "span"]
    assert "cascade.round" in spans


def test_tune_trace_events(tmp_path, capsys):
    from tpusvm.cli import main

    path = str(tmp_path / "tu.jsonl")
    rc = main(["tune", "--smoke", "--trace", path, "-q"])
    capsys.readouterr()
    assert rc == 0
    records = read_trace(path)
    points = [r["attrs"] for r in records if r["kind"] == "event"
              and r["name"] == "tune.point"]
    assert len(points) == 4  # the smoke 2x2 grid
    assert all(p["cv_accuracy"] is not None for p in points)
    winners = [r for r in records if r["kind"] == "event"
               and r["name"] == "tune.winner"]
    assert len(winners) == 1


# ----------------------------------------------- rotation (observability 8)
def test_tracer_rotation_and_rotated_read(tmp_path):
    from tpusvm.obs import default_registry, reset_default_registry

    reset_default_registry()
    path = str(tmp_path / "t.jsonl")
    with Tracer(path, clock=FakeClock(), wall=lambda: 100.0,
                max_bytes=600) as tr:
        for i in range(20):
            tr.event("tick", i=i)
    import os

    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 600
    records = read_trace(path)  # rotated set folded in, oldest first
    # a continuation meta re-opens each rotated file with the ORIGINAL
    # t0/wall so timestamps stay on one clock
    metas = [r for r in records if r["kind"] == "meta"]
    assert len(metas) >= 2 and metas[-1].get("rotated", 0) >= 1
    assert all(m["wall"] == 100.0 for m in metas)
    ticks = [r["attrs"]["i"] for r in records if r["kind"] == "event"
             and r["name"] == "tick"]
    assert ticks == sorted(ticks)  # chronological across the set
    # files the single-backup scheme displaced are COUNTED, not silent
    snap = {e["name"]: e["value"]
            for e in default_registry().snapshot()["metrics"]
            if e["type"] == "counter"}
    assert snap.get("obs.trace_rotations", 0) >= 2
    assert snap.get("obs.trace_dropped_records", 0) > 0
    reset_default_registry()


def test_tracer_without_max_bytes_never_rotates(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with Tracer(path, clock=FakeClock()) as tr:
        for i in range(50):
            tr.event("tick", i=i)
    import os

    assert not os.path.exists(path + ".1")
    assert len(read_trace(path)) == 52  # meta + 50 + end


# ----------------------------------- Prometheus text rendering edge cases
def test_render_text_escapes_label_values():
    from tpusvm.obs.registry import escape_label_value

    reg = MetricsRegistry()
    reg.counter("weird", path='a"b\\c\nd').inc(2)
    text = reg.render_text()
    # per the exposition format: backslash, quote and newline escaped
    assert 'tpusvm_weird_total{path="a\\"b\\\\c\\nd"} 2' in text
    assert escape_label_value('say "hi"') == 'say \\"hi\\"'
    assert escape_label_value("back\\slash") == "back\\\\slash"
    assert escape_label_value("two\nlines") == "two\\nlines"
    # and the escaping round-trips through a snapshot merge
    from tpusvm.obs.registry import render_snapshot_text

    merged = merge_snapshots(reg.snapshot(), reg.snapshot())
    assert 'path="a\\"b\\\\c\\nd"} 4' in render_snapshot_text(merged)


def test_render_text_histogram_inf_bucket_and_sum_count():
    reg = MetricsRegistry()
    h = reg.histogram("lat", bounds=(0.1, 1.0), model='m"x')
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    text = reg.render_text()
    lines = [line for line in text.splitlines() if "lat" in line]
    # cumulative buckets, +Inf closing at the total count
    assert any('le="0.1"} 1' in line for line in lines)
    assert any('le="1.0"} 2' in line for line in lines)
    assert any('le="+Inf"} 3' in line for line in lines)
    sums = [line for line in lines if "_sum" in line]
    counts = [line for line in lines if "_count" in line]
    assert len(sums) == 1 and sums[0].endswith(" 5.55")
    assert len(counts) == 1 and counts[0].endswith(" 3")
    # the label value is escaped inside bucket lines too
    assert any('model="m\\"x"' in line for line in lines)


def test_serve_metrics_text_escapes_model_label():
    import jax.numpy as jnp

    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server

    X, Y = rings(n=160, seed=9)
    model = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float64).fit(X, Y)
    with Server(ServeConfig(max_batch=2), dtype=jnp.float64) as srv:
        srv.add_model('quo"te', model)
        text = srv.metrics_text()
    assert 'model="quo\\"te"' in text


# ------------------------------------------------- multi-trace collation
def test_merge_trace_files_interleaves_by_wall_clock(tmp_path):
    from tpusvm.obs.report import merge_trace_files, phase_summary

    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    # same monotonic clocks, different wall offsets: b started 10s later
    with Tracer(a, clock=FakeClock(), wall=lambda: 1000.0) as tr:
        with tr.span("training", phase=True):
            tr.event("convergence.round", round=1, gap=0.5, updates=3,
                     status="RUNNING")
    with Tracer(b, clock=FakeClock(), wall=lambda: 1010.0) as tr:
        with tr.span("training", phase=True):
            tr.event("convergence.round", round=2, gap=0.1, updates=1,
                     status="CONVERGED")
    merged = merge_trace_files([a, b])
    assert all("_wall" in r and "_file" in r for r in merged)
    walls = [r["_wall"] for r in merged]
    assert walls == sorted(walls)
    # a's records all precede b's (10s offset >> the fake-clock ticks)
    files = [r["_file"] for r in merged]
    assert files.index(b) == len([f for f in files if f == a])
    acc, total = phase_summary(merged)
    # phases accumulate across files; total is the WALL envelope
    assert acc["training"] == pytest.approx(4.0)  # 2 ticks per span
    # envelope: a's meta at wall 1000 ... b's end record at wall 1014
    # (offset 1009 + the fake clock's 5th tick)
    assert total == pytest.approx(14.0)


def test_report_cli_over_directory(tmp_path, capsys):
    from tpusvm.cli import main

    d = tmp_path / "traces"
    d.mkdir()
    _write_demo_trace(str(d / "train.jsonl"))
    with Tracer(str(d / "worker.jsonl"), clock=FakeClock(),
                wall=lambda: 50.0) as tr:
        with tr.span("search", phase=True):
            pass
    assert main(["report", str(d), "--smoke"]) == 0
    out = capsys.readouterr().out
    assert "2 files" in out
    assert "training time: " in out and "search time: " in out

    # an empty directory is a clean error, not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit):
        main(["report", str(empty)])
