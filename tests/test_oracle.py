"""Oracle SMO tests: convergence, KKT properties, warm start.

The reference's validation is cross-implementation parity (SURVEY.md §4);
here the oracle additionally gets direct mathematical checks so it can anchor
that parity chain.
"""

import numpy as np

from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, blobs, rings
from tpusvm.oracle import get_sv_indices, predict, smo_train
from tpusvm.status import Status

CFG = SVMConfig(C=1.0, gamma=0.125)  # banknote/debug preset (main3.cpp:308-312)


def _train_scaled(gen, **kw):
    X, Y = gen(**kw)
    Xs = MinMaxScaler().fit_transform(X)
    return Xs, Y


def test_converges_on_blobs():
    Xs, Y = _train_scaled(blobs, n=120, seed=0)
    res = smo_train(Xs, Y, CFG)
    assert res.status == Status.CONVERGED
    # stopping criterion achieved (main3.cpp:213)
    assert res.b_low <= res.b_high + 2 * CFG.tau
    preds = predict(Xs, Xs, Y, res.alpha, res.b, CFG.gamma)
    assert (preds == Y).mean() > 0.97


def test_rbf_separates_rings():
    # not linearly separable — succeeds only if the RBF kernel path is right
    Xs, Y = _train_scaled(rings, n=200, seed=1)
    res = smo_train(Xs, Y, SVMConfig(C=10.0, gamma=10.0))
    assert res.status == Status.CONVERGED
    preds = predict(Xs, Xs, Y, res.alpha, res.b, 10.0)
    assert (preds == Y).mean() > 0.97


def test_kkt_properties():
    Xs, Y = _train_scaled(blobs, n=150, seed=2)
    res = smo_train(Xs, Y, CFG)
    a = res.alpha
    # box constraint
    assert (a >= -1e-12).all() and (a <= CFG.C + 1e-12).all()
    # dual feasibility: sum alpha_i y_i = 0 is preserved by every paired update
    assert abs(float(a @ Y)) < 1e-9
    assert len(get_sv_indices(a)) > 0


def test_warm_start_from_converged_solution_is_immediate():
    # cascade semantics: retraining from a converged alpha must converge in
    # one working-set check with no further updates (n_iter stays 1)
    Xs, Y = _train_scaled(blobs, n=100, seed=4)
    res = smo_train(Xs, Y, CFG)
    res2 = smo_train(Xs, Y, CFG, alpha0=res.alpha, warm_start=True)
    assert res2.status == Status.CONVERGED
    assert res2.n_iter == 1
    np.testing.assert_allclose(res2.alpha, res.alpha)
    np.testing.assert_allclose(res2.b, res.b, atol=1e-9)


def test_predict_vectorised_matches_per_row_loop():
    # the blockwise predict (VERDICT r3 #6) must agree with a literal
    # per-row evaluation of sign(sum a_k y_k K(x, x_k) - b)
    from tpusvm.oracle.smo import rbf_row

    Xs, Y = _train_scaled(rings, n=200, seed=6)
    res = smo_train(Xs, Y, SVMConfig(C=10.0, gamma=10.0))
    sv = get_sv_indices(res.alpha)
    coef = res.alpha[sv] * Y[sv]
    want = np.array([
        1 if float(coef @ rbf_row(Xs[sv], x, 10.0)) - res.b > 0 else -1
        for x in Xs
    ], np.int32)
    got = predict(Xs, Xs, Y, res.alpha, res.b, 10.0)
    np.testing.assert_array_equal(got, want)


def test_predict_empty_sv_set_scores_minus_b():
    X = np.random.default_rng(0).random((8, 3))
    alpha = np.zeros(5)
    Y = np.ones(5, np.int32)
    np.testing.assert_array_equal(
        predict(X, X[:5], Y, alpha, b=1.0, gamma=1.0), -np.ones(8, np.int32))
    np.testing.assert_array_equal(
        predict(X, X[:5], Y, alpha, b=-1.0, gamma=1.0), np.ones(8, np.int32))


def test_iteration_counter_reference_semantics():
    # n_iter = successful updates + 1 (main3.cpp:197, :281); a run capped at
    # max_iter must stop with MAX_ITER status
    Xs, Y = _train_scaled(blobs, n=100, seed=5)
    res = smo_train(Xs, Y, SVMConfig(C=1.0, gamma=0.125, max_iter=3))
    assert res.status == Status.MAX_ITER
    assert res.n_iter == 4  # 3 updates + 1, then > max_iter triggers
