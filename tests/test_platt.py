"""Platt-calibrated predict_proba on BinarySVC: the acceptance gates.

The committed calibration fixture is a deterministic noisy-rings problem
(label noise keeps scores informative but imperfect, so calibration has
something to gain). Gates: predict_proba is MONOTONE in
decision_function, and its held-out log-loss beats the uncalibrated
0/1-clipped baseline; plus serialization and estimator-surface coverage.
"""

import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import rings
from tpusvm.kernels.platt import log_loss
from tpusvm.models import BinarySVC, load_any
from tpusvm.tune.folds import stratified_kfold


def _calibration_fixture(n=360, n_test=120, seed=42, flip=0.08):
    """Noisy rings: deterministic, with flipped labels so the optimal
    probabilities are genuinely soft."""
    X, Y = rings(n=n + n_test, seed=seed)
    rng = np.random.default_rng(seed + 1)
    idx = rng.choice(n + n_test, int(flip * (n + n_test)), replace=False)
    Y = Y.copy()
    Y[idx] = -Y[idx]
    return X[:n], Y[:n], X[n:], Y[n:]


@pytest.fixture(scope="module")
def calibrated():
    X, Y, Xt, Yt = _calibration_fixture()
    model = BinarySVC(config=SVMConfig(C=10.0, gamma=10.0))
    model.fit(X, Y)
    model.calibrate(X, Y, folds=3, seed=0)
    return model, X, Y, Xt, Yt


def test_predict_proba_monotone_in_decision_function(calibrated):
    model, _, _, Xt, _ = calibrated
    scores = model.decision_function(Xt)
    proba = model.predict_proba(Xt)[:, 1]
    order = np.argsort(scores)
    assert np.all(np.diff(proba[order]) >= 0)
    # strictly increasing wherever scores differ
    ds = np.diff(scores[order])
    dp = np.diff(proba[order])
    assert np.all(dp[ds > 1e-9] > 0)


def test_predict_proba_beats_clipped_baseline(calibrated):
    model, _, _, Xt, Yt = calibrated
    proba = model.predict_proba(Xt)[:, 1]
    baseline = (model.decision_function(Xt) > 0).astype(float)
    assert log_loss(proba, Yt) < log_loss(baseline, Yt)


def test_predict_proba_rows_sum_to_one(calibrated):
    model, _, _, Xt, _ = calibrated
    p = model.predict_proba(Xt)
    assert p.shape == (len(Xt), 2)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-12)
    assert np.all((p >= 0) & (p <= 1))


def test_calibration_used_held_out_scores(calibrated):
    # the pooled calibration scores must come from fold models, not the
    # full model: refitting the fold split reproduces the protocol and
    # the SAME (A, B) — a regression to in-sample scoring would diverge
    model, X, Y, _, _ = calibrated
    scores = np.empty(len(Y))
    for fold in stratified_kfold(Y, 3, seed=0):
        sub = BinarySVC(config=model.config)
        sub.fit(X[fold.train_idx], Y[fold.train_idx])
        scores[fold.val_idx] = sub.decision_function(X[fold.val_idx])
    from tpusvm.kernels.platt import fit_platt

    A, B = fit_platt(scores, Y)
    assert model.platt_ == (A, B)


def test_platt_roundtrips_through_npz(tmp_path, calibrated):
    model, _, _, Xt, _ = calibrated
    p = str(tmp_path / "cal.npz")
    model.save(p)
    loaded = load_any(p)
    assert loaded.platt_ == model.platt_
    np.testing.assert_array_equal(loaded.predict_proba(Xt),
                                  model.predict_proba(Xt))


def test_uncalibrated_model_save_has_no_platt(tmp_path):
    X, Y, _, _ = _calibration_fixture(n=160, n_test=1)
    model = BinarySVC(config=SVMConfig(C=10.0, gamma=10.0)).fit(X, Y)
    p = str(tmp_path / "plain.npz")
    model.save(p)
    assert load_any(p).platt_ is None
    with pytest.raises(RuntimeError, match="not calibrated"):
        load_any(p).predict_proba(X)


def test_predict_proba_requires_calibration():
    X, Y, _, _ = _calibration_fixture(n=160, n_test=1)
    model = BinarySVC(config=SVMConfig(C=10.0, gamma=10.0)).fit(X, Y)
    with pytest.raises(RuntimeError, match="not calibrated"):
        model.predict_proba(X)
