"""bench.py headline-protection machinery, exercised with synthetic faults.

The unattended round-end benchmark must never lose the headline to a
kernel regression: a tiny-shape runtime canary picks a working kernel
layout before the heavy compile, and a compile-failure chain degrades
packed -> flat -> XLA engine. These tests drive bench.main() end-to-end
on a shrunken workload with the kernel monkeypatched to fail in each
way, asserting the emitted record says which engine ran and why.

The TPU-only canary branch is exercised by faking the device platform;
kernel calls are redirected to interpret mode (true math, no Mosaic).
"""

import contextlib
import io
import json

import numpy as np
import pytest

import tpusvm.ops.pallas.inner_smo as ism


@pytest.fixture()
def bench_mod(monkeypatch):
    import bench

    real_mnist = bench.mnist_like
    monkeypatch.setattr(
        bench, "mnist_like",
        lambda **kw: real_mnist(n=512, d=32, noise=3.0, label_noise=0.005),
    )
    yield bench


def _run(bench):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert np.isfinite(rec["value"])
    # every record self-describes its data provenance (VERDICT r4 #4):
    # the headline trains a SYNTHETIC instance and must say so in the
    # one JSON line a dashboard ingests
    assert rec["workload"]["synthetic"] is True
    assert rec["workload"]["gen"] == "mnist_like"
    return rec["detail"]


class _FakeTPU:
    platform = "tpu"

    def __repr__(self):
        return "FakeTPU"


@pytest.fixture()
def fake_tpu(monkeypatch, bench_mod):
    real_devices = bench_mod.jax.devices

    def devices(*args, **kw):
        return [_FakeTPU()] if not args else real_devices(*args, **kw)

    monkeypatch.setattr(bench_mod.jax, "devices", devices)
    # consumers monkeypatch ism.inner_smo_pallas themselves (their
    # fault-injecting wrappers redirect surviving calls to interpret
    # mode, since the canary assumes a real TPU and passes
    # interpret=False while the actual backend is CPU)
    return ism.inner_smo_pallas


def test_bench_plain_cpu_uses_xla_engine(bench_mod):
    d = _run(bench_mod)
    assert d["engine"] == "xla"
    assert d["compile_fallback"] is None
    assert d["canary_passed"] is None  # non-TPU: canary not applicable
    assert d["init_fallback"] is None
    # VERDICT r3: a degraded record must carry the EFFECTIVE solver
    # config — on CPU the requested q=2048/selection=auto resolve to q=n
    # and selection=exact (the non-TPU resolution of 'auto'); wss=2 runs
    # as requested since the XLA engine implements second-order selection
    # (round 4)
    assert d["solver_config"] == {
        "q": 512,  # clamped to the shrunken fixture's n
        "inner": "xla",
        "wss": 2,
        "selection": "exact",
        "max_inner": 32768,  # the deeper CPU-fallback inner budget
        "max_outer": 5000,
        "fused_fupdate": False,  # 'auto' resolves off on a CPU backend
    }


def test_bench_canary_packed_fault_selects_flat(bench_mod, fake_tpu,
                                                monkeypatch):
    orig = fake_tpu

    def broken_packed(*a, **kw):
        if kw.get("layout", "packed") == "packed":
            raise RuntimeError("synthetic packed runtime fault")
        kw["interpret"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(ism, "inner_smo_pallas", broken_packed)
    d = _run(bench_mod)
    assert d["engine"] == "pallas-flat"
    assert "packed canary" in d["compile_fallback"]
    assert d["canary_passed"] is True  # flat WAS vetted


def test_bench_canary_total_fault_degrades_to_xla(bench_mod, fake_tpu,
                                                  monkeypatch):
    def broken_all(*a, **kw):
        raise RuntimeError("synthetic kernel runtime fault")

    monkeypatch.setattr(ism, "inner_smo_pallas", broken_all)
    d = _run(bench_mod)
    assert d["engine"] == "xla"
    assert "packed canary" in d["compile_fallback"]
    assert "flat canary" in d["compile_fallback"]
    # the canary delivered a verdict and the engine that runs is the
    # always-correct XLA loop — a vetted degraded run, not an unvetted one
    assert d["canary_passed"] is True


def test_bench_fused_canary_failure_pins_fused_off(bench_mod, fake_tpu,
                                                   monkeypatch):
    """round-4 adoption: the fused f-update kernel is vetted before the
    heavy compile. On this CPU backend the real kernel cannot run with
    interpret=False, so the fused canary fails organically — the run must
    pin fused_fupdate=False, record why, and still produce a headline."""
    orig = fake_tpu

    def interpret_inner(*a, **kw):
        kw["interpret"] = True
        return orig(*a, **kw)

    # keep the inner-kernel canary green so the fused pinning is isolated
    monkeypatch.setattr(ism, "inner_smo_pallas", interpret_inner)
    d = _run(bench_mod)
    assert "fused canary" in (d["compile_fallback"] or "")
    assert d["solver_config"]["fused_fupdate"] is False


def test_bench_fused_canary_pass_keeps_auto(bench_mod, fake_tpu,
                                            monkeypatch):
    """When the fused canary passes, fused_fupdate stays 'auto' (no
    pinning, no fallback note) — the backend-time resolution decides."""
    import tpusvm.ops.pallas.fused_fupdate as ff

    orig_inner = fake_tpu

    def interpret_inner(*a, **kw):
        kw["interpret"] = True
        return orig_inner(*a, **kw)

    monkeypatch.setattr(ism, "inner_smo_pallas", interpret_inner)
    orig_fused = ff.rbf_cross_matvec_pallas
    monkeypatch.setattr(
        ff, "rbf_cross_matvec_pallas",
        lambda *a, **kw: orig_fused(*a, **{**kw, "interpret": True}),
    )
    d = _run(bench_mod)
    assert "fused canary" not in (d["compile_fallback"] or "")
    # the record still says False: 'auto' resolves by the REAL backend
    # (cpu here), which is exactly the self-description we want
    assert d["solver_config"]["fused_fupdate"] is False


def test_bench_canary_harness_crash_marks_unvetted(bench_mod, fake_tpu,
                                                   monkeypatch):
    import tpusvm.ops.rbf as rbf_mod

    def broken_rbf_cross(*a, **kw):
        raise RuntimeError("synthetic canary-harness fault")

    # rbf_cross breaking fails the harness BEFORE the per-layout loop:
    # the distinct-marker path (ADVICE r2) — engine stays the intended
    # config but the record must say it ran unvetted. The canary imports
    # rbf_cross freshly inside main() (module-attribute lookup -> sees the
    # patch) while the solver bound its own reference at import time and
    # keeps working — a genuinely canary-only fault, unlike breaking
    # _inner_smo, which is the XLA inner engine itself (that only ever
    # "passed" here by cache-hitting a sibling test's jit lowering)
    monkeypatch.setattr(rbf_mod, "rbf_cross", broken_rbf_cross)
    d = _run(bench_mod)
    assert d["canary_passed"] is False
    assert "canary harness failed" in d["compile_fallback"]


# --- backend-init insurance (the BENCH_r02 rc=1 failure mode) ---
# Round 2's headline was lost because jax.devices() raised/hung before any
# fallback machinery could engage; these tests fault-inject every stage of
# the init chain: probe says dead -> CPU re-exec; probe passes but
# jax.devices raises -> CPU re-exec; even the CPU child yields no record
# -> last-resort record. Plus one REAL end-to-end child run.


def test_bench_init_probe_failure_triggers_cpu_reexec(bench_mod,
                                                      monkeypatch):
    calls = {}
    monkeypatch.setattr(bench_mod, "_should_probe", lambda: True)
    monkeypatch.setattr(bench_mod, "_probe_backend",
                        lambda: "synthetic: backend init hang")

    def fake_reexec(err):
        calls["err"] = err
        raise SystemExit(0)

    monkeypatch.setattr(bench_mod, "_reexec_cpu", fake_reexec)
    with pytest.raises(SystemExit):
        bench_mod.main()
    assert calls["err"] == "synthetic: backend init hang"


def test_should_probe_survives_private_api_removal(bench_mod, monkeypatch):
    """ADVICE r3: _should_probe leans on the private
    jax._src.xla_bridge.backends_are_initialized; if a JAX upgrade removes
    it, the guard must conservatively probe anyway rather than crash the
    benchmark before any fallback engages."""
    import jax
    from jax._src import xla_bridge

    monkeypatch.delenv(bench_mod._FORCE_CPU_ENV, raising=False)
    monkeypatch.delenv(bench_mod._ACCEL_CHILD_ENV, raising=False)
    # un-pin the conftest's cpu platform for the duration of the check
    # (jax_platforms is a read-only class property; monkeypatch restores)
    monkeypatch.setattr(type(jax.config), "jax_platforms",
                        property(lambda self: ""))
    monkeypatch.delattr(xla_bridge, "backends_are_initialized")
    assert bench_mod._should_probe() is True


def test_bench_probe_pass_runs_supervised_accel_child(bench_mod,
                                                      monkeypatch):
    calls = []
    monkeypatch.setattr(bench_mod, "_should_probe", lambda: True)
    monkeypatch.setattr(bench_mod, "_probe_backend", lambda: None)

    def fake_supervised():
        calls.append("supervised")
        raise SystemExit(0)

    monkeypatch.setattr(bench_mod, "_run_supervised_accel", fake_supervised)
    with pytest.raises(SystemExit):
        bench_mod.main()
    assert calls == ["supervised"]


def test_bench_supervised_accel_hang_degrades_to_cpu(bench_mod,
                                                     monkeypatch):
    """A post-probe wedge (child produces no record within the timeout)
    must degrade to the CPU re-exec — the residual window of a
    probe-only design."""
    import subprocess as sp

    def hang(*a, **kw):
        raise sp.TimeoutExpired(cmd=a[0], timeout=kw.get("timeout", 0))

    calls = {}
    monkeypatch.setattr(bench_mod.subprocess, "run", hang)

    def fake_reexec(err):
        calls["err"] = err
        raise SystemExit(0)

    monkeypatch.setattr(bench_mod, "_reexec_cpu", fake_reexec)
    with pytest.raises(SystemExit):
        bench_mod._run_supervised_accel()
    assert "hung" in calls["err"]


def test_bench_supervised_accel_forwards_child_record(bench_mod,
                                                      monkeypatch,
                                                      capsys):
    class GoodChild:
        stdout = '{"metric": "m", "value": 1.0}\n'
        returncode = 0

    monkeypatch.setattr(bench_mod.subprocess, "run",
                        lambda *a, **kw: GoodChild())
    with pytest.raises(SystemExit) as ei:
        bench_mod._run_supervised_accel()
    assert ei.value.code == 0
    assert json.loads(capsys.readouterr().out.strip())["value"] == 1.0


def test_bench_init_raise_in_accel_child_reraises_for_parent(bench_mod,
                                                             monkeypatch):
    """Inside the supervised accelerator child, a fast init raise must
    propagate (nonzero exit) so the SUPERVISING parent runs the single
    CPU fallback — a nested _reexec_cpu here would start a 5400s CPU
    measurement inside the parent's 1800s window, get killed
    mid-measurement, and orphan the grandchild."""
    calls = {}
    monkeypatch.setattr(bench_mod, "_should_probe", lambda: False)
    monkeypatch.setenv("_TPUSVM_BENCH_ACCEL_CHILD", "1")
    monkeypatch.setattr(
        bench_mod.jax, "devices",
        lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("Unable to initialize backend 'axon'")),
    )
    monkeypatch.setattr(bench_mod, "_reexec_cpu",
                        lambda err: calls.setdefault("err", err))
    with pytest.raises(RuntimeError, match="Unable to initialize"):
        bench_mod.main()
    assert "err" not in calls  # the child did NOT nest a CPU fallback


def test_bench_init_raise_outside_children_triggers_cpu_reexec(bench_mod,
                                                               monkeypatch):
    """A direct (non-supervised, non-forced) run whose init raises still
    degrades via _reexec_cpu."""
    calls = {}
    monkeypatch.setattr(bench_mod, "_should_probe", lambda: False)
    monkeypatch.delenv("_TPUSVM_BENCH_ACCEL_CHILD", raising=False)
    monkeypatch.setattr(
        bench_mod.jax, "devices",
        lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("Unable to initialize backend 'axon'")),
    )

    def fake_reexec(err):
        calls["err"] = err
        raise SystemExit(0)

    monkeypatch.setattr(bench_mod, "_reexec_cpu", fake_reexec)
    with pytest.raises(SystemExit):
        bench_mod.main()
    assert "Unable to initialize backend" in calls["err"]


def test_bench_reexec_emits_last_resort_record_when_child_dies(
        bench_mod, monkeypatch, capsys):
    class DeadChild:
        stdout = "no json here\n"
        returncode = 3

    monkeypatch.setattr(bench_mod.subprocess, "run",
                        lambda *a, **kw: DeadChild())
    with pytest.raises(SystemExit) as ei:
        bench_mod._reexec_cpu("synthetic: total backend outage")
    assert ei.value.code == 0  # a record was emitted: rc must be 0
    out = capsys.readouterr().out
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["workload"]["synthetic"] is True
    assert rec["detail"]["init_fallback"] == "synthetic: total backend outage"
    assert rec["detail"]["cpu_child_rc"] == 3


def test_bench_cpu_fallback_child_end_to_end():
    """REAL child process: the exact path a wedged TPU triggers, minus the
    probe timeout — bench.py re-run with the CPU pin + recorded init error
    (shrunken workload via the smoke env knob). Asserts the emitted record
    is a complete degraded measurement."""
    import os
    import subprocess
    import sys

    bench_path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    env = {**os.environ,
           "_TPUSVM_BENCH_FORCE_CPU": "1",
           "_TPUSVM_BENCH_INIT_ERROR": "synthetic: tunnel wedged",
           "_TPUSVM_BENCH_SMOKE": "1"}
    p = subprocess.run([sys.executable, bench_path], env=env,
                       capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, p.stderr[-2000:]
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    assert np.isfinite(rec["value"])
    assert rec["workload"]["synthetic"] is True
    d = rec["detail"]
    assert d["platform"] == "cpu"
    assert d["engine"] == "xla"
    assert d["init_fallback"] == "synthetic: tunnel wedged"
