"""bench.py headline-protection machinery, exercised with synthetic faults.

The unattended round-end benchmark must never lose the headline to a
kernel regression: a tiny-shape runtime canary picks a working kernel
layout before the heavy compile, and a compile-failure chain degrades
packed -> flat -> XLA engine. These tests drive bench.main() end-to-end
on a shrunken workload with the kernel monkeypatched to fail in each
way, asserting the emitted record says which engine ran and why.

The TPU-only canary branch is exercised by faking the device platform;
kernel calls are redirected to interpret mode (true math, no Mosaic).
"""

import contextlib
import io
import json

import numpy as np
import pytest

import tpusvm.ops.pallas.inner_smo as ism


@pytest.fixture()
def bench_mod(monkeypatch):
    import bench

    real_mnist = bench.mnist_like
    monkeypatch.setattr(
        bench, "mnist_like",
        lambda **kw: real_mnist(n=512, d=32, noise=3.0, label_noise=0.005),
    )
    yield bench


def _run(bench):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.main()
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert np.isfinite(rec["value"])
    return rec["detail"]


class _FakeTPU:
    platform = "tpu"

    def __repr__(self):
        return "FakeTPU"


@pytest.fixture()
def fake_tpu(monkeypatch, bench_mod):
    real_devices = bench_mod.jax.devices

    def devices(*args, **kw):
        return [_FakeTPU()] if not args else real_devices(*args, **kw)

    monkeypatch.setattr(bench_mod.jax, "devices", devices)
    # consumers monkeypatch ism.inner_smo_pallas themselves (their
    # fault-injecting wrappers redirect surviving calls to interpret
    # mode, since the canary assumes a real TPU and passes
    # interpret=False while the actual backend is CPU)
    return ism.inner_smo_pallas


@pytest.mark.filterwarnings(
    # off TPU, bench's tuned wss=2 degrades to first-order on the XLA
    # engine with this warning — the documented off-TPU behaviour
    "ignore:wss=2 requested:RuntimeWarning"
)
def test_bench_plain_cpu_uses_xla_engine(bench_mod):
    d = _run(bench_mod)
    assert d["engine"] == "xla"
    assert d["compile_fallback"] is None


@pytest.mark.filterwarnings(
    # the faked TPU platform makes the canary run while the real backend
    # is CPU, so the heavy solve's inner='auto' resolves to the XLA
    # engine and the requested wss=2 legitimately degrades with this
    # warning — expected for this fault-injection setup only
    "ignore:wss=2 requested:RuntimeWarning"
)
def test_bench_canary_packed_fault_selects_flat(bench_mod, fake_tpu,
                                                monkeypatch):
    orig = fake_tpu

    def broken_packed(*a, **kw):
        if kw.get("layout", "packed") == "packed":
            raise RuntimeError("synthetic packed runtime fault")
        kw["interpret"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(ism, "inner_smo_pallas", broken_packed)
    d = _run(bench_mod)
    assert d["engine"] == "pallas-flat"
    assert "packed canary" in d["compile_fallback"]


@pytest.mark.filterwarnings(
    "ignore:wss=2 requested:RuntimeWarning"  # see sibling test
)
def test_bench_canary_total_fault_degrades_to_xla(bench_mod, fake_tpu,
                                                  monkeypatch):
    def broken_all(*a, **kw):
        raise RuntimeError("synthetic kernel runtime fault")

    monkeypatch.setattr(ism, "inner_smo_pallas", broken_all)
    d = _run(bench_mod)
    assert d["engine"] == "xla"
    assert "packed canary" in d["compile_fallback"]
    assert "flat canary" in d["compile_fallback"]
