"""Closed-loop online learning: drift detectors, crash-safe supervisor
state, the autopilot tick loop (breaker/hysteresis/cooldown/watchdog),
and the OvR/SVR refresh satellites (tpusvm/autopilot/, serve/refresh.py,
tune/warm.py)."""

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm import faults
from tpusvm.autopilot import (
    Autopilot,
    AutopilotConfig,
    AutopilotState,
    DriftThresholds,
    evaluate,
    load_state,
    save_state,
)
from tpusvm.autopilot.drift import feature_drift, score_shift
from tpusvm.config import SVMConfig
from tpusvm.data import rings
from tpusvm.models import BinarySVC
from tpusvm.serve import ServeConfig, Server
from tpusvm.status import AutopilotStatus
from tpusvm.stream import ShardWriter, ingest_arrays, open_dataset

X, Y = rings(n=400, seed=11)
CFG = SVMConfig(C=10.0, gamma=10.0)


def _deploy(tmp_path, n=240):
    """Dataset dir over the prefix + a deployed artifact trained on it."""
    data = str(tmp_path / "data")
    ingest_arrays(data, X[:n], Y[:n], rows_per_shard=64)
    deployed = str(tmp_path / "deployed.npz")
    BinarySVC(CFG, dtype=jnp.float32).fit(X[:n], Y[:n]).save(deployed)
    return data, deployed


def _grow(data, start=240, end=400, step=40):
    w = ShardWriter.open_append(data)
    for s in range(start, end, step):
        w.append(X[s:s + step], Y[s:s + step])
    w.close()


def _config(tmp_path, data, deployed, **kw):
    base = dict(
        data_dir=data, model_path=deployed,
        out_path=str(tmp_path / "m.refresh.npz"), name="m",
        thresholds=DriftThresholds(growth=0.5, feature=None,
                                   score=None, jitter_frac=0.0),
        hysteresis=1, cooldown_s=0.0, seed=3,
    )
    base.update(kw)
    return AutopilotConfig(**base)


# --------------------------------------------------------------- drift
def test_drift_report_byte_reproducible_by_seed(tmp_path):
    data, _ = _deploy(tmp_path)
    ds = open_dataset(data)
    kw = dict(manifest=ds.manifest, fitted_min=X.min(0),
              fitted_max=X.max(0), rows_at_refresh=160,
              since_refresh_s=12.5, score_baseline={"pos": 50, "neg": 50},
              score_current={"pos": 80, "neg": 120},
              thresholds=DriftThresholds(jitter_frac=0.2), seed=9, tick=4)
    a = evaluate(**kw).to_json_bytes()
    b = evaluate(**kw).to_json_bytes()
    assert a == b
    # a different seed jitters the thresholds differently
    c = evaluate(**{**kw, "seed": 10}).to_json_bytes()
    assert c != a
    # the report is schema-versioned JSON
    obj = json.loads(a)
    assert obj["schema_version"] == 1 and obj["seed"] == 9


def test_feature_drift_math(tmp_path):
    data, _ = _deploy(tmp_path)
    ds = open_dataset(data)
    # fitted range = the full data's range: nothing escapes
    fd = feature_drift(ds.manifest, X.min(0), X.max(0), 0)
    assert fd["score"] == 0.0 and fd["appended_rows"] == ds.n_rows
    # shrink the fitted max by half the range: escapes are relative
    lo, hi = X.min(0), X.max(0)
    mid = lo + 0.5 * (hi - lo)
    fd = feature_drift(ds.manifest, lo, mid, 0)
    assert fd["score"] > 0.9 and fd["frac_escaped"] == 1.0
    # no appended shards -> exact zero, no bytes read
    fd = feature_drift(ds.manifest, lo, hi, ds.n_rows)
    assert fd == {"score": 0.0, "frac_escaped": 0.0, "appended_rows": 0}


def test_score_shift_windows_delta_counts():
    base = {"pos": 60, "neg": 40}
    # post-baseline traffic flipped to 20% positive: shift = 0.4
    cur = {"pos": 60 + 20, "neg": 40 + 80}
    ss = score_shift(base, cur)
    assert ss["window"] == 100
    assert ss["rate_base"] == pytest.approx(0.6)
    assert ss["rate_now"] == pytest.approx(0.2)
    assert ss["score"] == pytest.approx(0.4)
    # no post-baseline traffic: no shift claimed
    assert score_shift(base, base)["score"] == 0.0


def test_staleness_and_min_new_rows_gating(tmp_path):
    data, _ = _deploy(tmp_path)
    ds = open_dataset(data)
    thr = DriftThresholds(feature=None, growth=None, score=None,
                          staleness_s=100.0, min_new_rows=10)
    r = evaluate(manifest=ds.manifest, fitted_min=None, fitted_max=None,
                 rows_at_refresh=ds.n_rows, since_refresh_s=50.0,
                 score_baseline=None, score_current=None,
                 thresholds=thr, seed=0, tick=1)
    assert not r.decision
    # staleness may trigger WITHOUT new rows (its whole point)
    r = evaluate(manifest=ds.manifest, fitted_min=None, fitted_max=None,
                 rows_at_refresh=ds.n_rows, since_refresh_s=150.0,
                 score_baseline=None, score_current=None,
                 thresholds=thr, seed=0, tick=1)
    assert r.decision and "staleness" in r.reason
    # growth triggers are suppressed below min_new_rows
    thr2 = DriftThresholds(feature=None, growth=0.0001, score=None,
                           min_new_rows=10 ** 6)
    r = evaluate(manifest=ds.manifest, fitted_min=None, fitted_max=None,
                 rows_at_refresh=1, since_refresh_s=0.0,
                 score_baseline=None, score_current=None,
                 thresholds=thr2, seed=0, tick=1)
    assert not r.decision and "min_new_rows" in r.reason


# --------------------------------------------------------------- state
def test_state_roundtrip_crc_and_version_gates(tmp_path):
    p = str(tmp_path / "s.json")
    st = AutopilotState(seed=7, tick=3, rows_at_refresh=240,
                        stage="fitting", stage_rows=400,
                        model_path="m.npz",
                        score_baseline={"pos": 1, "neg": 2},
                        breaker={"state": "closed", "consecutive": 0,
                                 "opened_at": 0.0})
    save_state(p, st)
    back = load_state(p)
    assert back == st
    # CRC catches a torn/hand-edited file
    obj = json.load(open(p))
    obj["rows_at_refresh"] = 9999
    json.dump(obj, open(p, "w"))
    with pytest.raises(ValueError, match="CRC"):
        load_state(p)
    # version gate names the problem
    obj = {"state_version": 99}
    json.dump(obj, open(p, "w"))
    with pytest.raises(ValueError, match="version"):
        load_state(p)
    json.dump({"x": 1}, open(p, "w"))
    with pytest.raises(ValueError, match="state_version"):
        load_state(p)


def test_breaker_snapshot_restore_replays_cooldown():
    clock = [100.0]
    b = faults.CircuitBreaker(threshold=2, cooldown_s=50.0,
                              clock=lambda: clock[0])
    b.record_failure()
    b.record_failure()
    assert b.state == "open"
    snap = b.snapshot()
    b2 = faults.CircuitBreaker(threshold=2, cooldown_s=50.0,
                               clock=lambda: clock[0])
    b2.restore(snap)
    assert b2.state == "open" and not b2.allow()
    clock[0] = 151.0
    assert b2.state == "half_open" and b2.allow()
    with pytest.raises(ValueError, match="breaker state"):
        b2.restore({"state": "bogus", "consecutive": 0, "opened_at": 0})


# ----------------------------------------------------------- tick loop
def test_tick_hysteresis_cooldown_and_refresh(tmp_path):
    data, deployed = _deploy(tmp_path)
    clock = [1000.0]
    with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
        srv.load_model("m", deployed)
        srv.warmup()
        pilot = Autopilot(
            _config(tmp_path, data, deployed, hysteresis=2,
                    cooldown_s=30.0),
            server=srv, clock=lambda: clock[0], log_fn=lambda m: None)
        assert pilot.tick()["status"] == AutopilotStatus.WATCHING
        _grow(data)
        out = pilot.tick()
        assert out["status"] == AutopilotStatus.TRIGGERED_HYSTERESIS
        out = pilot.tick()
        assert out["status"] == AutopilotStatus.REFRESHED
        assert srv.registry.generation("m") == 2
        # served scores == the refreshed artifact's offline scores
        scores, _ = srv.predict_direct("m", X[:8])
        offline = BinarySVC.load(pilot.cfg.out_path, dtype=jnp.float32)
        assert np.array_equal(
            scores, np.asarray(offline.decision_function(X[:8])))
        # a fresh trigger inside the cooldown window: the first tick is
        # hysteresis 1/2, the second would refresh but the cooldown
        # suppresses it
        w = ShardWriter.open_append(data)
        w.append(np.tile(X[:200], (2, 1))[:400], np.tile(Y[:200], 2)[:400])
        w.close()
        clock[0] += 10.0
        assert pilot.tick()["status"] \
            == AutopilotStatus.TRIGGERED_HYSTERESIS
        out = pilot.tick()
        assert out["status"] == AutopilotStatus.SUPPRESSED_COOLDOWN
        # past the cooldown it refreshes again
        clock[0] += 30.0
        out = pilot.tick()
        assert out["status"] == AutopilotStatus.REFRESHED
        assert srv.registry.generation("m") == 3


def test_refresh_failure_loop_cannot_hot_loop(tmp_path):
    """The acceptance pin: with refreshes failing persistently, the
    breaker trips after `breaker_threshold` attempts and every
    subsequent eligible tick is SUPPRESSED_BREAKER (no further refresh
    attempts) until the injectable clock passes the cooldown, when
    exactly one half-open probe is admitted."""
    data, deployed = _deploy(tmp_path)
    clock = [0.0]
    pilot = Autopilot(
        _config(tmp_path, data, deployed, breaker_threshold=2,
                breaker_cooldown_s=100.0),
        server=None, clock=lambda: clock[0], log_fn=lambda m: None)
    _grow(data)
    plan = faults.FaultPlan(
        [faults.FaultRule(point="autopilot.refresh", kind="transient",
                          p=1.0)], seed=0)
    with faults.active(plan):
        s1 = pilot.tick()["status"]
        s2 = pilot.tick()["status"]
        assert (s1, s2) == (AutopilotStatus.REFRESH_FAILED,) * 2
        attempts_at_trip = plan.hits("autopilot.refresh")
        assert attempts_at_trip == 2
        # breaker is OPEN: ticks keep watching, refresh NEVER attempted
        for _ in range(5):
            assert pilot.tick()["status"] \
                == AutopilotStatus.SUPPRESSED_BREAKER
        assert plan.hits("autopilot.refresh") == attempts_at_trip
        assert pilot.state.failures == 2
        # past the cooldown: exactly one half-open probe goes out
        clock[0] = 101.0
        assert pilot.tick()["status"] == AutopilotStatus.REFRESH_FAILED
        assert plan.hits("autopilot.refresh") == attempts_at_trip + 1
        assert pilot.tick()["status"] \
            == AutopilotStatus.SUPPRESSED_BREAKER
    # faults cleared + cooldown passed: the probe succeeds and the
    # loop recovers (artifact-drop mode: no server to swap into)
    clock[0] = 202.0
    assert pilot.tick()["status"] == AutopilotStatus.REFRESHED
    assert os.path.exists(pilot.cfg.out_path)


def test_watchdog_timeout_then_resume_bit_identical(tmp_path):
    """A hung (here: deadline-zero) fit is stopped at a checkpointed
    segment boundary; the next eligible tick RESUMES it from its own
    checkpoint, and the final artifact is bit-identical to an
    uninterrupted refresh."""
    from tpusvm.serve.refresh import refresh_fit

    data, deployed = _deploy(tmp_path)
    clock = [0.0]
    pilot = Autopilot(
        _config(tmp_path, data, deployed,
                checkpoint_path=str(tmp_path / "ck.npz"),
                checkpoint_every=1, deadline_s=0.0,
                breaker_threshold=100, breaker_cooldown_s=0.0),
        server=None, clock=lambda: clock[0], log_fn=lambda m: None)
    _grow(data)
    # deadline 0 + checkpoint_every 1 => the FIRST durable segment
    # checkpoint trips the watchdog
    out = pilot.tick()
    assert out["status"] == AutopilotStatus.REFRESH_TIMEOUT
    assert pilot.state.stage == "fitting"
    assert os.path.exists(str(tmp_path / "ck.npz"))
    # lift the deadline: the resumed fit completes from the checkpoint
    pilot.cfg = dataclasses.replace(pilot.cfg, deadline_s=None)
    out = pilot.tick()
    assert out["status"] == AutopilotStatus.REFRESHED
    refreshed = BinarySVC.load(pilot.cfg.out_path)
    # uninterrupted control with the SAME config, same data
    Xg, Yg = open_dataset(data).load_arrays()
    plain = refresh_fit(deployed, Xg, Yg,
                        out_path=str(tmp_path / "plain.npz"))
    assert refreshed.sv_alpha_.tobytes() == plain.sv_alpha_.tobytes()
    assert np.array_equal(refreshed.sv_ids_, plain.sv_ids_)
    assert refreshed.b_ == plain.b_


def test_kill_mid_refresh_resume_replays_decisions(tmp_path):
    """Kill the supervisor at the refresh stage; a resumed supervisor
    (same seed, same state file) finishes the SAME refresh and the
    artifact is bit-identical to an uninterrupted run's."""
    from tpusvm.serve.refresh import refresh_fit

    data, deployed = _deploy(tmp_path)
    cfg = _config(tmp_path, data, deployed,
                  checkpoint_path=str(tmp_path / "ck.npz"),
                  checkpoint_every=1)
    pilot = Autopilot(cfg, server=None, log_fn=lambda m: None)
    _grow(data)
    plan = faults.FaultPlan(
        [faults.FaultRule(point="solver.outer_checkpoint", kind="kill",
                          at_hit=1)], seed=0)
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            pilot.tick()
    # the killed supervisor's state froze mid-stage
    st = load_state(cfg.resolved().state_path)
    assert st.stage == "fitting" and st.stage_rows == 400
    pilot2 = Autopilot(cfg, server=None, resume=True,
                       log_fn=lambda m: None)
    out = pilot2.tick()
    assert out["status"] == AutopilotStatus.REFRESHED
    assert pilot2.state.rows_at_refresh == 400
    Xg, Yg = open_dataset(data).load_arrays()
    plain = refresh_fit(deployed, Xg, Yg,
                        out_path=str(tmp_path / "plain.npz"))
    got = BinarySVC.load(pilot2.cfg.out_path)
    assert got.sv_alpha_.tobytes() == plain.sv_alpha_.tobytes()
    assert np.array_equal(got.sv_ids_, plain.sv_ids_)
    assert got.b_ == plain.b_


def test_resume_seed_mismatch_refused(tmp_path):
    data, deployed = _deploy(tmp_path)
    cfg = _config(tmp_path, data, deployed, seed=1)
    Autopilot(cfg, log_fn=lambda m: None)   # writes the state file
    with pytest.raises(ValueError, match="seed"):
        Autopilot(dataclasses.replace(cfg, seed=2), resume=True,
                  log_fn=lambda m: None)


def test_autopilot_obs_counters_and_trace_events(tmp_path):
    from tpusvm.obs.registry import default_registry, reset_default_registry

    reset_default_registry()
    events = []
    faults.set_event_sink(lambda name, **attrs: events.append((name, attrs)))
    try:
        data, deployed = _deploy(tmp_path)
        pilot = Autopilot(_config(tmp_path, data, deployed),
                          log_fn=lambda m: None)
        pilot.tick()
        _grow(data)
        pilot.tick()
        reg = default_registry()
        snap = {(e["name"], tuple(sorted(e["labels"].items()))): e
                for e in reg.snapshot()["metrics"]}
        assert snap[("autopilot.ticks", ())]["value"] == 2
        assert snap[("autopilot.refreshes_triggered", ())]["value"] == 1
        assert ("autopilot.drift_score",
                (("detector", "row_growth"),)) in snap
        assert snap[("autopilot.data_staleness_rows", ())]["value"] \
            == 160.0
        drift_events = [a for n, a in events if n == "autopilot.drift"]
        assert len(drift_events) == 2
        assert drift_events[1]["decision"] is True
        assert drift_events[1]["report"]["schema_version"] == 1
    finally:
        faults.set_event_sink(None)
        reset_default_registry()


def test_report_renders_autopilot_section():
    from tpusvm.obs.report import autopilot_rows, format_autopilot_table

    recs = [
        {"kind": "event", "name": "autopilot.drift",
         "attrs": {"tick": 1, "decision": False, "reason": "no",
                   "report": {"detectors": [
                       {"name": "row_growth", "score": 0.1,
                        "threshold": 0.5, "triggered": False}]}}},
        {"kind": "event", "name": "autopilot.drift",
         "attrs": {"tick": 2, "decision": True,
                   "reason": "triggered: row_growth",
                   "report": {"detectors": [
                       {"name": "row_growth", "score": 0.7,
                        "threshold": 0.5, "triggered": True}]}}},
        {"kind": "span", "name": "x", "t0": 0, "t1": 1, "dur_s": 1,
         "attrs": {}},
    ]
    rows = autopilot_rows(recs)
    assert len(rows) == 2
    table = format_autopilot_table(rows)
    assert "REFRESH" in table and "row_growth=0.7/0.5*" in table
    assert "triggered: row_growth" in table
    assert format_autopilot_table([]) \
        == "no autopilot decisions in this trace"


# ----------------------------------------- served-score drift plumbing
def test_serve_score_sign_counters_feed_score_shift(tmp_path):
    data, deployed = _deploy(tmp_path)
    with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
        srv.load_model("m", deployed)
        srv.warmup()
        base = srv.score_stats("m")
        assert base == {"pos": 0, "neg": 0}
        for i in range(24):
            r = srv.submit("m", X[i])
            assert r.ok
        cur = srv.score_stats("m")
        assert cur["pos"] + cur["neg"] == 24
        assert cur["pos"] > 0 and cur["neg"] > 0  # rings has both signs
        ss = score_shift(base, cur)
        assert ss["window"] == 24


# --------------------------------------------- OvR / SVR refresh tasks
def test_refresh_ovr_warm_parity_and_savings(tmp_path):
    from tpusvm.data.synthetic import mnist_like_multiclass
    from tpusvm.models import OneVsRestSVC
    from tpusvm.serve.refresh import refresh_fit

    Xm, Ym = mnist_like_multiclass(n=300, d=24, seed=5)
    cfg = SVMConfig(C=10.0, gamma=0.5)
    dep = str(tmp_path / "ovr.npz")
    OneVsRestSVC(cfg, solver="blocked").fit(Xm[:200], Ym[:200]).save(dep)
    warm = refresh_fit(dep, Xm, Ym, out_path=str(tmp_path / "w.npz"))
    cold = refresh_fit(dep, Xm, Ym, out_path=str(tmp_path / "c.npz"),
                       warm=False)
    # parity at the solution level: same SV union, same accuracy,
    # every head converged — and the warm seed does real work
    assert np.array_equal(warm.sv_ids_, cold.sv_ids_)
    assert warm.score(Xm, Ym) == cold.score(Xm, Ym)
    assert all(s == 1 for s in warm.statuses_)
    assert int(warm.n_iter_.sum()) < int(cold.n_iter_.sum())
    # the refreshed artifact round-trips sv_ids (the new state field)
    back = OneVsRestSVC.load(str(tmp_path / "w.npz"))
    assert np.array_equal(back.sv_ids_, warm.sv_ids_)


def test_refresh_ovr_artifact_without_sv_ids_needs_cold(tmp_path):
    """Pre-0.18 OvR artifacts (no sv_ids) refresh cold with a named
    error on the warm path."""
    from tpusvm.models import OneVsRestSVC
    from tpusvm.serve.refresh import refresh_fit

    Xm = X[:200]
    Ym = np.where(Y[:200] > 0, 3, 7)
    cfg = SVMConfig(C=10.0, gamma=10.0)
    dep = str(tmp_path / "old.npz")
    m = OneVsRestSVC(cfg, solver="blocked").fit(Xm, Ym)
    m.sv_ids_ = None   # simulate a pre-0.18 artifact
    m.save(dep)
    with pytest.raises(ValueError, match="sv_ids"):
        refresh_fit(dep, Xm, Ym, out_path=str(tmp_path / "w.npz"))
    cold = refresh_fit(dep, Xm, Ym, out_path=str(tmp_path / "c.npz"),
                       warm=False)
    assert cold.score(Xm, Ym) > 0.8


def test_refresh_svr_warm_parity_and_savings(tmp_path):
    from tpusvm.data.synthetic import svr_sine
    from tpusvm.models import EpsilonSVR
    from tpusvm.serve.refresh import refresh_fit

    Xs, t = svr_sine(n=300, d=2, seed=5)
    cfg = SVMConfig(C=10.0, gamma=1.0, epsilon=0.1)
    dep = str(tmp_path / "svr.npz")
    EpsilonSVR(cfg).fit(Xs[:200], t[:200]).save(dep)
    warm = refresh_fit(dep, Xs, t, out_path=str(tmp_path / "w.npz"))
    cold = refresh_fit(dep, Xs, t, out_path=str(tmp_path / "c.npz"),
                       warm=False)
    assert warm.status_.name == "CONVERGED"
    assert np.array_equal(warm.sv_ids_, cold.sv_ids_)
    assert warm.n_iter_ < cold.n_iter_
    assert warm.score(Xs, t) > 0.9
    back = EpsilonSVR.load(str(tmp_path / "w.npz"))
    assert np.array_equal(back.sv_ids_, warm.sv_ids_)


def test_refresh_ovr_svr_reject_checkpoint_by_name(tmp_path):
    from tpusvm.models import OneVsRestSVC
    from tpusvm.serve.refresh import refresh_fit

    Xm = X[:160]
    Ym = np.where(Y[:160] > 0, 1, 2)
    dep = str(tmp_path / "ovr.npz")
    OneVsRestSVC(SVMConfig(C=10.0, gamma=10.0),
                 solver="blocked").fit(Xm, Ym).save(dep)
    with pytest.raises(ValueError, match="future PR"):
        refresh_fit(dep, Xm, Ym, out_path=str(tmp_path / "o.npz"),
                    checkpoint_path=str(tmp_path / "ck.npz"))


def test_deployed_seed_ovr_and_svr_constructions():
    from tpusvm.tune.warm import deployed_seed_ovr, deployed_seed_svr

    # OvR: |coef| scatters per head, feasible per head's labels
    ids = np.array([0, 2])
    coef = np.array([[1.0, -1.0], [-2.0, 2.0]])
    labels = np.array([5, 7, 5, 7])
    seeds = deployed_seed_ovr(ids, coef, 4, labels,
                              np.array([5, 7]), C=10.0)
    assert seeds.shape == (2, 4)
    for k, c in enumerate([5, 7]):
        yk = np.where(labels == c, 1, -1)
        assert float(np.sum(seeds[k] * yk)) == pytest.approx(0.0)
    with pytest.raises(ValueError, match="prefix"):
        deployed_seed_ovr(np.array([9]), coef[:, :1], 4, labels,
                          np.array([5, 7]), C=10.0)
    # SVR: the doubling inverts sign-exactly and stays feasible
    beta = deployed_seed_svr(np.array([0, 1]), np.array([1.5, -2.0]),
                             3, C=10.0)
    assert beta.shape == (6,)
    Y2 = np.concatenate([np.ones(3), -np.ones(3)])
    assert float(np.sum(beta * Y2)) == pytest.approx(0.0)
    assert beta[0] > 0 and beta[4] > 0 and beta[1] == 0.0
    with pytest.raises(ValueError, match="prefix"):
        deployed_seed_svr(np.array([5]), np.array([1.0]), 3, C=10.0)


def test_ovr_warm_seeds_requires_blocked_solver():
    from tpusvm.models import OneVsRestSVC

    m = OneVsRestSVC(SVMConfig(), solver="pair")
    with pytest.raises(ValueError, match="blocked"):
        m.fit(X[:64], np.where(Y[:64] > 0, 1, 2),
              warm_seeds=np.zeros((2, 64)))
