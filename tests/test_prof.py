"""Tests for the compile observatory (tpusvm.obs.prof / obs.costs).

Contracts:
  * BIT-TRANSPARENCY (the acceptance bar): a solve with profiling
    enabled produces identical alpha bytes / SV ids / b to one with it
    off — the AOT executable is the same program the jit cache builds;
  * one compile record per distinct signature: repeat calls hit the
    cache, scalar-hyperparameter changes (a C/gamma sweep) share one
    executable exactly like jit's own weak-type rule;
  * tracer passthrough: a wrapped entry point called inside another
    trace (jit/vmap) does not try to AOT-compile tracers;
  * cost/memory normalisation (obs.costs) across the dict / list /
    absent shapes cost_analysis() has had;
  * `tpusvm report` renders a compile table with a nonzero
    compile-time + FLOPs row from a train --trace run (or the explicit
    unavailable marker).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpusvm.config import SVMConfig  # noqa: E402
from tpusvm.data import rings  # noqa: E402
from tpusvm.models import BinarySVC  # noqa: E402
from tpusvm.obs import costs, prof  # noqa: E402
from tpusvm.obs.registry import MetricsRegistry  # noqa: E402
from tpusvm.solver.blocked import blocked_smo_solve  # noqa: E402


@pytest.fixture(autouse=True)
def _profiling_off():
    yield
    prof.disable_profiling()


def _fit(X, Y, **cfg_kw):
    return BinarySVC(config=SVMConfig(C=10.0, gamma=10.0, **cfg_kw)).fit(X, Y)


# ------------------------------------------------------------ transparency
def test_profiled_solve_bit_transparent():
    X, Y = rings(n=240, seed=3)
    base = _fit(X, Y)
    with prof.profiling(registry=MetricsRegistry()):
        profiled = _fit(X, Y)
    assert np.asarray(base.sv_alpha_).tobytes() == \
        np.asarray(profiled.sv_alpha_).tobytes()
    assert np.array_equal(base.sv_ids_, profiled.sv_ids_)
    assert base.b_ == profiled.b_
    assert np.array_equal(
        np.asarray(base.decision_function(X[:16])),
        np.asarray(profiled.decision_function(X[:16])),
    )


# ---------------------------------------------------------------- records
def test_compile_event_and_gauges():
    X, Y = rings(n=200, seed=5)
    events = []
    reg = MetricsRegistry()
    with prof.profiling(registry=reg,
                        event_sink=lambda n, **a: events.append((n, a))):
        _fit(X, Y)
    solver = [a for n, a in events
              if n == "prof.compile"
              and a["executable"] == "solver.blocked_smo_solve"]
    assert len(solver) == 1
    rec = solver[0]
    assert rec["compile_s"] > 0 and rec["lower_s"] > 0
    # either the backend priced it (nonzero FLOPs) or it is marked absent
    if rec["cost_available"]:
        assert rec["flops"] > 0
        assert rec["arith_intensity"] is not None
    else:
        assert rec["flops"] is None
    snap = {(e["name"], tuple(sorted(e["labels"].items()))): e
            for e in reg.snapshot()["metrics"]}
    key = ("prof.compiles",
           (("executable", "solver.blocked_smo_solve"),))
    assert snap[key]["value"] == 1
    assert ("prof.compile_s",
            (("executable", "solver.blocked_smo_solve"),)) in snap


def test_compile_cache_and_weak_scalar_key():
    X, Y = rings(n=200, seed=5)
    events = []
    with prof.profiling(registry=MetricsRegistry(),
                        event_sink=lambda n, **a: events.append(a)):
        _fit(X, Y)
        n1 = len(events)
        _fit(X, Y)                       # identical call: cache hit
        assert len(events) == n1
        # a (C, gamma) change is a TRACED-scalar change — shares the
        # executable exactly like jit's own cache
        BinarySVC(config=SVMConfig(C=1.0, gamma=2.0)).fit(X, Y)
        assert len(events) == n1
        # a shape change is a new signature -> one new compile
        X2, Y2 = rings(n=150, seed=6)
        _fit(X2, Y2)
        assert len(events) > n1


def test_tracer_passthrough_under_jit():
    from tpusvm.solver.predict import decision_function

    X, Y = rings(n=64, seed=1)
    coef = jnp.zeros((64,), jnp.float32).at[0].set(1.0)
    Xd = jnp.asarray(X, jnp.float32)
    direct = np.asarray(decision_function(Xd[:8], Xd, coef, 0.0, gamma=1.0))
    events = []
    with prof.profiling(registry=MetricsRegistry(),
                        event_sink=lambda n, **a: events.append(a)):
        wrapped = jax.jit(
            lambda q: decision_function(q, Xd, coef, 0.0, gamma=1.0)
        )
        out = np.asarray(wrapped(Xd[:8]))
    # inside the outer jit the wrapper saw tracers: no AOT attempt, and
    # numerics match the direct path
    assert not events
    np.testing.assert_array_equal(direct, out)


def test_profiled_jit_preserves_surface():
    import inspect

    assert hasattr(blocked_smo_solve, "lower")
    params = inspect.signature(blocked_smo_solve).parameters
    assert "q" in params and "telemetry" in params
    # the AOT surface still lowers/compiles (benchmarks use it directly)
    X, Y = rings(n=96, seed=2)
    compiled = blocked_smo_solve.lower(
        jnp.asarray(X, jnp.float32), jnp.asarray(Y), C=10.0, gamma=10.0,
        q=32, accum_dtype=jnp.float32,
    ).compile()
    res = compiled(jnp.asarray(X, jnp.float32), jnp.asarray(Y),
                   C=10.0, gamma=10.0)
    assert int(res.n_iter) >= 1


def test_record_compile_without_observatory():
    reg = MetricsRegistry()
    rec = prof.record_compile("serve.bucket[m:b4]", 0.01, 0.2,
                              compiled=None, registry=reg, bucket=4)
    assert rec["cost_available"] is False
    entries = {e["name"] for e in reg.snapshot()["metrics"]}
    assert "prof.compiles" in entries and "prof.compile_s" in entries


# ------------------------------------------------------------------ costs
class _FakeCompiled:
    def __init__(self, cost, mem=None):
        self._cost, self._mem = cost, mem

    def cost_analysis(self):
        if isinstance(self._cost, Exception):
            raise self._cost
        return self._cost

    def memory_analysis(self):
        return self._mem


def test_cost_summary_shapes():
    d = {"flops": 10.0, "bytes accessed": 5.0}
    assert costs.cost_summary(_FakeCompiled(d)) == {
        "available": True, "flops": 10.0, "bytes_accessed": 5.0}
    lst = [{"flops": 4.0, "bytes accessed": 2.0}, {"flops": 6.0}]
    s = costs.cost_summary(_FakeCompiled(lst))
    assert s["flops"] == 10.0 and s["bytes_accessed"] == 2.0
    for bad in (None, [], RuntimeError("no cost model")):
        s = costs.cost_summary(_FakeCompiled(bad))
        assert s == {"available": False, "flops": None,
                     "bytes_accessed": None}


def test_arithmetic_intensity_edges():
    assert costs.arithmetic_intensity(10.0, 5.0) == 2.0
    assert costs.arithmetic_intensity(None, 5.0) is None
    assert costs.arithmetic_intensity(10.0, None) is None
    assert costs.arithmetic_intensity(10.0, 0.0) is None


def test_compile_record_unavailable_marker():
    rec = costs.compile_record("x", 0.1, 0.2, _FakeCompiled(None))
    assert rec["cost_available"] is False and rec["flops"] is None


# --------------------------------------------------------- report surface
def test_format_compile_table_marks_unavailable():
    from tpusvm.obs.report import format_compile_table

    rows = [
        {"executable": "solver.blocked_smo_solve", "lower_s": 0.1,
         "compile_s": 0.5, "cost_available": True, "flops": 2e9,
         "bytes_accessed": 1e8},
        {"executable": "cascade.round_fn", "lower_s": 0.2,
         "compile_s": 0.9, "cost_available": False, "flops": None,
         "bytes_accessed": None},
    ]
    table = format_compile_table(rows)
    assert "solver.blocked_smo_solve" in table
    assert "cost_analysis: unavailable" in table
    assert "no compile records" in format_compile_table([])


def test_train_trace_report_shows_compile_table(tmp_path, capsys):
    from tpusvm.cli import main

    trace = str(tmp_path / "t.jsonl")
    assert main(["train", "--platform", "cpu", "--smoke", "-q",
                 "--trace", trace]) == 0
    capsys.readouterr()
    assert main(["report", trace]) == 0
    out = capsys.readouterr().out
    assert "compiles (lower/compile wall time" in out
    assert "solver.blocked_smo_solve" in out
    # the acceptance bar: >= 1 executable row with nonzero compile time
    # and FLOPs, or an explicit unavailable marker
    from tpusvm.obs import read_trace
    from tpusvm.obs.report import compile_rows

    rows = compile_rows(read_trace(trace))
    assert rows
    assert any(r["compile_s"] > 0 and
               (r["flops"] or not r["cost_available"]) for r in rows)


def test_serve_bucket_compiles_recorded():
    from tpusvm.serve import ServeConfig, Server

    X, Y = rings(n=200, seed=4)
    model = _fit(X, Y)
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        srv.add_model("m", model)
        srv.warmup()
        snap = srv._worker("m").metrics.registry_snapshot()
    names = {(e["name"], e["labels"].get("executable"))
             for e in snap["metrics"]}
    assert any(n == "prof.compiles" and x and x.startswith("serve.bucket[")
               for n, x in names)
