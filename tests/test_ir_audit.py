"""Tests for tpusvm.analysis.ir — the jaxpr-level semantic auditor.

Contracts:
  * the registry traces at least 8 real entry points on CPU, and the
    repo's own traces audit CLEAN against the EMPTY committed baseline;
  * every JXIR rule fires on its known-bad corpus entry
    (tests/analysis_corpus/ir/) and ONLY that rule fires there;
  * a deliberately unrouted dot_general fails the gate (the regression
    fixture the acceptance criterion names);
  * the committed audit artifact (benchmarks/results/ir_audit_cpu.json)
    matches the schema and carries zero findings;
  * two audit runs produce byte-identical artifacts (determinism);
  * the baseline mechanism grandfathers findings exactly like the AST
    linter's.

The full audit traces every entry point once (~3 s on CPU); it runs
once per module via a session fixture and every structural test reads
from it.
"""

import importlib.util
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from tpusvm.analysis.ir.audit import (
    AUDIT_SCHEMA_VERSION,
    render_audit_json,
    run_ir_audit,
)
from tpusvm.analysis.ir.entrypoints import IREntryPoint, default_entrypoints
from tpusvm.analysis.ir.rules import IR_RULE_SUMMARIES, all_ir_rules

REPO = Path(__file__).resolve().parent.parent
IR_CORPUS = REPO / "tests" / "analysis_corpus" / "ir"
ARTIFACT = REPO / "benchmarks" / "results" / "ir_audit_cpu.json"

JXIR_IDS = ("JXIR101", "JXIR102", "JXIR103", "JXIR104", "JXIR105",
            "JXIR106")


@pytest.fixture(scope="module")
def full_audit():
    return run_ir_audit()


def _load_corpus(path: Path):
    spec = importlib.util.spec_from_file_location(
        f"ir_corpus_{path.stem}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------- registry
def test_all_six_rules_registered():
    rules = all_ir_rules()
    assert tuple(sorted(rules)) == JXIR_IDS
    assert tuple(sorted(IR_RULE_SUMMARIES)) == JXIR_IDS
    for rid, rule in rules.items():
        assert rule.id == rid and rule.summary


def test_rule_summaries_importable_without_tracing():
    # the lint CI job lists IR rules with no accelerator deps; the
    # summaries path must not pull jax in at import time
    import subprocess
    import sys

    code = ("import sys; sys.modules['jax'] = None; "
            "from tpusvm.analysis.ir.rules import IR_RULE_SUMMARIES; "
            "assert len(IR_RULE_SUMMARIES) == 6")
    res = subprocess.run([sys.executable, "-c", code], cwd=str(REPO),
                         capture_output=True, text=True)
    assert res.returncode == 0, res.stderr


def test_entrypoint_names_unique_and_required_present():
    names = [e.name for e in default_entrypoints()]
    assert len(names) == len(set(names))
    required = {
        "solver.blocked_smo_solve", "solver.smo_solve",
        "solver.shrink_segment", "predict.decision_function",
        "predict.decision_function_flat", "predict.ovr_scores",
        "serve.bucket[binary]", "serve.bucket[ovr]",
        "kernels.cross_matvec[rbf]", "kernels.cross_matvec[linear]",
        "kernels.cross_matvec[poly]", "cascade.round_fn",
    }
    assert required <= set(names), sorted(required - set(names))


# ------------------------------------------------------------ repo audit
def test_repo_audits_clean_with_at_least_eight_entries(full_audit):
    assert full_audit.traced_count >= 8, [
        (e.name, e.skip_reason) for e in full_audit.entries]
    assert full_audit.findings == [], "\n".join(
        f.render() + " :: " + f.message for f in full_audit.findings)
    # skipped entries must say why
    for e in full_audit.entries:
        if not e.traced:
            assert e.skip_reason


def test_pallas_body_is_walked(full_audit):
    fused = {e.name: e for e in full_audit.entries}[
        "solver.blocked_smo_solve[fused]"]
    if not fused.traced:  # pragma: no cover — env without pallas
        pytest.skip(fused.skip_reason)
    assert fused.stats["pallas_calls"] >= 1


def test_swept_entries_declare_scalars(full_audit):
    swept = [e for e in full_audit.entries if e.swept]
    assert len(swept) >= 5  # solvers + kernel dispatch sweeps
    assert any("C" in e.swept for e in swept)


# ------------------------------------------------------------- IR corpus
@pytest.mark.parametrize("rule_id", JXIR_IDS)
def test_rule_fires_on_its_ir_corpus_entry(rule_id):
    matches = sorted(IR_CORPUS.glob(f"{rule_id.lower()}_*.py"))
    assert matches, f"no IR corpus file for {rule_id}"
    mod = _load_corpus(matches[0])
    assert mod.RULE == rule_id
    res = run_ir_audit(entries=[mod.ENTRY])
    fired = {f.rule for f in res.findings}
    assert rule_id in fired, f"{rule_id} did not fire; got {fired}"
    # single-hazard corpus discipline, like the AST corpus
    assert fired == {rule_id}, (
        f"extra rules fired on {matches[0].name}: {fired - {rule_id}}")
    assert res.exit_code == 1


def test_every_registered_rule_has_a_corpus_entry():
    for rid in all_ir_rules():
        assert sorted(IR_CORPUS.glob(f"{rid.lower()}_*.py")), (
            f"rule {rid} has no tests/analysis_corpus/ir/ case")


# ------------------------------------------- the unrouted-dot regression
def test_gate_fails_on_deliberately_unrouted_dot_general():
    """The acceptance fixture: introduce an entry whose contraction
    skips the precision resolver and the audit must fail."""

    def build():
        def f_update(K, coef):
            return K @ coef  # unrouted on purpose

        s = jax.ShapeDtypeStruct
        return f_update, (s((1024, 256), jnp.float32),
                          s((256,), jnp.float32)), {}

    bad = IREntryPoint(name="regression.unrouted_dot", build=build)
    res = run_ir_audit(entries=default_entrypoints() + [bad])
    assert res.exit_code == 1
    hits = [f for f in res.findings if f.rule == "JXIR101"]
    assert hits and all(
        f.path == "jaxpr://regression.unrouted_dot" for f in hits)


def test_bf16_pattern_rejected_outside_bf16_rung():
    def build():
        def f(a, b):
            return jnp.matmul(a.astype(jnp.bfloat16),
                              b.astype(jnp.bfloat16),
                              preferred_element_type=jnp.float32)

        s = jax.ShapeDtypeStruct
        return f, (s((128, 128), jnp.float32),
                   s((128, 128), jnp.float32)), {}

    # same trace: a finding on the f32 rung, clean on the bf16 rung
    bad = IREntryPoint(name="t.bf16_on_f32_rung", build=build)
    res = run_ir_audit(entries=[bad])
    assert {f.rule for f in res.findings} == {"JXIR101"}
    ok = IREntryPoint(name="t.bf16_on_bf16_rung", build=build,
                      precision="bf16_f32")
    assert run_ir_audit(entries=[ok]).findings == []


# ---------------------------------------------------- committed artifact
def test_committed_artifact_schema():
    doc = json.loads(ARTIFACT.read_text(encoding="utf-8"))
    assert doc["version"] == AUDIT_SCHEMA_VERSION
    assert doc["tool"] == "tpusvm.analysis.ir"
    assert tuple(sorted(doc["rules"])) == JXIR_IDS
    assert doc["findings"] == []          # the empty-baseline contract
    assert doc["counts"] == {}
    assert doc["traced_entry_points"] >= 8
    names = [e["name"] for e in doc["entry_points"]]
    assert "solver.blocked_smo_solve" in names
    assert "kernels.cross_matvec[poly]" in names
    for e in doc["entry_points"]:
        assert set(e) == {"name", "description", "precision", "traced",
                          "skip_reason", "swept_scalars", "stats"}
        if e["traced"]:
            assert e["stats"]["eqns"] > 0
        else:
            assert e["skip_reason"]
    for f in doc["findings"]:  # schema of findings, if any ever land
        assert set(f) == {"rule", "path", "line", "col", "message",
                          "snippet", "fingerprint"}


def test_committed_artifact_matches_current_registry():
    doc = json.loads(ARTIFACT.read_text(encoding="utf-8"))
    committed = {e["name"] for e in doc["entry_points"]}
    current = {e.name for e in default_entrypoints()}
    assert committed == current, (
        "registry drifted from the committed artifact — regenerate with "
        "`python -m tpusvm.analysis ir-audit --json-out "
        "benchmarks/results/ir_audit_cpu.json`")


def test_committed_baseline_is_empty():
    from tpusvm.analysis.baseline import load_baseline

    assert load_baseline(REPO / ".tpusvm-ir-baseline.json") == set()


# ------------------------------------------------------------ determinism
def test_audit_is_deterministic(full_audit):
    again = run_ir_audit()
    assert render_audit_json(full_audit) == render_audit_json(again)


# --------------------------------------------------------------- baseline
def test_baseline_grandfathers_ir_findings(tmp_path):
    from tpusvm.analysis.baseline import load_baseline, write_baseline

    mod = _load_corpus(next(iter(
        sorted(IR_CORPUS.glob("jxir101_*.py")))))
    res = run_ir_audit(entries=[mod.ENTRY])
    assert res.findings
    bl = tmp_path / "ir-baseline.json"
    write_baseline(bl, res.findings)
    res2 = run_ir_audit(entries=[mod.ENTRY], baseline=load_baseline(bl))
    assert res2.findings == []
    assert len(res2.baselined) == len(res.findings)
    assert res2.exit_code == 0


def test_fingerprints_stable_across_runs():
    mod = _load_corpus(next(iter(
        sorted(IR_CORPUS.glob("jxir104_*.py")))))
    f1 = run_ir_audit(entries=[mod.ENTRY]).findings
    f2 = run_ir_audit(entries=[mod.ENTRY]).findings
    assert [f.fingerprint for f in f1] == [f.fingerprint for f in f2]
    assert all(len(f.fingerprint) == 12 for f in f1)


# -------------------------------------------------------------------- CLI
def test_cli_smoke_ok(capsys):
    from tpusvm.analysis.ir.cli import main

    rc = main(["--smoke", "--baseline",
               str(REPO / ".tpusvm-ir-baseline.json")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "--smoke ok" in out


def test_cli_dispatch_from_analysis_cli(capsys):
    from tpusvm.analysis.cli import main

    rc = main(["ir-audit", "--list-rules"])
    assert rc == 0
    out = capsys.readouterr().out
    for rid in JXIR_IDS:
        assert rid in out


def test_cli_list_entries(capsys):
    from tpusvm.analysis.ir.cli import main

    assert main(["--list-entries"]) == 0
    out = capsys.readouterr().out
    assert "solver.blocked_smo_solve" in out
    assert "cascade.round_fn" in out


def test_cli_unknown_rule_is_usage_error(capsys):
    from tpusvm.analysis.ir.cli import main

    assert main(["--select", "JXIR999"]) == 2


def test_cli_unknown_entry_is_usage_error(capsys):
    from tpusvm.analysis.ir.cli import main

    assert main(["--entry", "no.such.entry"]) == 2
