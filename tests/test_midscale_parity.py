"""Opt-in mid-scale oracle parity (VERDICT r3 #2).

Demonstrates the reference's cross-implementation parity criterion
(/root/reference/README.md:88-89: identical SV sets, b agreement <0.003%,
equal accuracy between its serial and accelerator builds) at a size where
the blocked solver's production machinery — q-sized top-k working sets,
subproblem caps, approx selection — actually engages, instead of the
n<=200 geometry of tests/test_solver_parity.py.

Opt-in because the float64 NumPy oracle takes minutes at n=2048:

    TPUSVM_RUN_MIDSCALE=1 python -m pytest tests/test_midscale_parity.py

The committed capture of the same harness at n ∈ {2048, 4096, 8192,
16384, 32768} lives in benchmarks/results/midscale_parity_cpu.jsonl
(16384/32768 rows: f64 pair exact at every size; f32 engines identical
SV sets except one 32768 boundary flip, absolute b agreement ≤1.4e-4 —
see the results README for the |b|-scale context on the strict band).
"""

import os

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("TPUSVM_RUN_MIDSCALE") != "1",
    reason="mid-scale oracle parity is slow (minutes); opt in with "
           "TPUSVM_RUN_MIDSCALE=1",
)


def test_midscale_parity_n2048():
    from benchmarks.midscale_parity import run_size

    rows, summary = run_size(2048)
    for engine in ("pair-f64", "blocked-exact", "blocked-approx",
                   "blocked-exact-wss2", "blocked-approx-wss2",
                   "blocked-cpu-bench-config"):
        verdict = summary[engine]
        assert verdict["sv_set_identical"], (engine, verdict)
        assert verdict["b_within_0.003pct"], (engine, verdict)
        assert verdict["accuracy_equal"], (engine, verdict)
