"""Tests for the bench regression gate (tpusvm.obs.benchdiff).

Contracts (the acceptance bars):
  * SELF-DIFF of every committed benchmarks/results/*.jsonl artifact is
    clean (exit 0) — the gate can read the whole committed history;
  * the committed synthetic regression fixture pair FAILS (exit != 0),
    at full AND smoke (direction-only) levels;
  * cross-backend comparisons are REFUSED by default (the r02-r05
    CPU-fallback trap) and annotated under --allow-cross-backend;
  * a baseline row with no counterpart is a regression (a silently
    skipped bench), an extra new row is only a note;
  * text/json/markdown renderings carry the verdict.
"""

import glob
import json
import os

import pytest

from tpusvm.obs import benchdiff

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(ROOT, "benchmarks", "results")
FIXTURES = os.path.join(ROOT, "tests", "fixtures", "benchdiff")


def _cli(*argv):
    from tpusvm.cli import main

    return main(["benchdiff", *argv])


# ---------------------------------------------------------------- self-diff
@pytest.mark.parametrize(
    "path", sorted(glob.glob(os.path.join(RESULTS, "*.jsonl"))),
    ids=os.path.basename,
)
def test_self_diff_of_committed_artifacts_is_clean(path, capsys):
    assert _cli(path, path) == 0, capsys.readouterr().out


def test_regression_fixture_fails_full_and_smoke(capsys):
    base = os.path.join(FIXTURES, "baseline.jsonl")
    reg = os.path.join(FIXTURES, "regressed.jsonl")
    assert _cli(base, reg) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "verdict: FAIL" in out
    assert "qps" in out  # the throughput drop is flagged at full level
    assert _cli(base, reg, "--level", "smoke") == 1
    out = capsys.readouterr().out
    # direction-only: wall-clock metrics are skipped, correctness still gates
    assert "errors" in out and "qps" not in out
    # and the baseline is self-clean in both levels
    assert _cli(base, base) == 0
    assert _cli(base, base, "--level", "smoke") == 0


# --------------------------------------------------------------- provenance
def _rows(backend):
    return [{"bench": "serve_latency", "mode": "batched", "threads": 8,
             "qps": 100.0, "errors": 0,
             "provenance": {"backend": backend}}]


def test_cross_backend_refused_by_default():
    res = benchdiff.diff_records(_rows("tpu"), _rows("cpu"))
    assert not res.ok
    assert res.refusals and "cpu" in res.refusals[0].message
    assert res.to_text().startswith("benchdiff")
    assert "REFUSED" in res.to_text()


def test_cross_backend_annotated_when_allowed():
    res = benchdiff.diff_records(_rows("tpu"), _rows("cpu"),
                                 allow_cross_backend=True)
    assert res.ok
    assert any(f.kind == "note" and f.metric == "provenance"
               for f in res.findings)


def test_platform_field_is_provenance_fallback():
    old = [{"bench": "x", "platform": "tpu"}]
    new = [{"bench": "x", "platform": "cpu"}]
    res = benchdiff.diff_records(old, new)
    assert res.refusals


# ------------------------------------------------------------ row matching
def test_missing_baseline_row_is_regression():
    old = [{"bench": "b", "n": 1, "violations": []},
           {"bench": "b", "n": 2, "violations": []}]
    new = [{"bench": "b", "n": 1, "violations": []}]
    res = benchdiff.diff_records(old, new)
    assert any("no counterpart" in f.message for f in res.regressions)


def test_extra_new_row_is_only_a_note():
    old = [{"bench": "b", "n": 1, "violations": []}]
    new = old + [{"bench": "b", "n": 2, "violations": []}]
    res = benchdiff.diff_records(old, new)
    assert res.ok
    assert any(f.kind == "note" for f in res.findings)


def test_unknown_schema_default_rules():
    old = [{"whatever": 1, "violations": [], "bit_identical": True}]
    bad = [{"whatever": 1, "violations": ["boom"], "bit_identical": False}]
    assert benchdiff.diff_records(old, old).ok
    res = benchdiff.diff_records(old, bad)
    assert {f.metric for f in res.regressions} == \
        {"violations", "bit_identical"}


def test_tolerance_bands_hold_at_equality_and_for_negatives():
    # "<=" with a negative old value must not tighten (overhead_frac can
    # legitimately be -0.5%)
    old = [{"bench": "telemetry_overhead", "overhead_frac": -0.01,
            "bit_identical": True, "violations": [],
            "status": "CONVERGED"}]
    new = [dict(old[0], overhead_frac=0.005)]
    assert benchdiff.diff_records(old, new).ok  # within +0.02 abs band
    worse = [dict(old[0], overhead_frac=0.03)]
    assert not benchdiff.diff_records(old, worse).ok


# ------------------------------------------------------------------ output
def test_json_and_markdown_formats(capsys):
    base = os.path.join(FIXTURES, "baseline.jsonl")
    reg = os.path.join(FIXTURES, "regressed.jsonl")
    assert _cli(base, reg, "--format", "json") == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is False
    assert any(f["kind"] == "regression" for f in payload["findings"])
    assert _cli(base, reg, "--format", "markdown") == 1
    md = capsys.readouterr().out
    assert "**FAIL**" in md and "| regression |" in md


def test_unreadable_input_is_a_clean_error(tmp_path, capsys):
    bad = tmp_path / "bad.jsonl"
    bad.write_text("not json\n")
    base = os.path.join(FIXTURES, "baseline.jsonl")
    assert _cli(base, str(bad)) == 1
    assert "benchdiff:" in capsys.readouterr().out
    assert _cli(base, str(tmp_path / "missing.jsonl")) == 1
