"""Compiled-Mosaic tests for the fused inner-SMO kernel — TPU only.

tests/test_pallas.py exercises inner_smo_pallas in INTERPRET mode on CPU;
until now the compiled-Mosaic lowering was validated only as a side effect
of bench.py. These tests assert compiled-kernel vs XLA inner-loop agreement
on a genuine mid-solve working set, so a Mosaic lowering regression is
caught before it can silently corrupt the benchmark headline.

Run with the real backend kept (tests/conftest.py forces CPU otherwise):

    TPUSVM_TEST_PLATFORM=native python -m pytest tests/test_pallas_tpu.py -v

Skips when the backend is not a TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.data import MinMaxScaler, rings
from tpusvm.ops.rbf import rbf_cross
from tpusvm.ops.selection import i_high_mask, i_low_mask
from tpusvm.solver.blocked import _inner_smo, blocked_smo_solve
from tpusvm.status import Status

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="compiled-Mosaic test; run with TPUSVM_TEST_PLATFORM=native on "
    "a TPU host",
)

C, GAMMA, EPS, TAU = 10.0, 10.0, 1e-12, 1e-5
Q = 128  # = n: the whole problem is the subproblem (lane-aligned)


@pytest.fixture(scope="module")
def midsolve_subproblem():
    """A genuine mid-solve state: run the blocked solver to a small update
    budget, then rebuild the exact f for the resulting alpha."""
    X, Y = rings(n=Q, seed=3)
    Xs = MinMaxScaler().fit_transform(X).astype(np.float32)
    Xd = jnp.asarray(Xs)
    Yd = jnp.asarray(Y)
    r = blocked_smo_solve(
        Xd, Yd, C=C, gamma=GAMMA, eps=EPS, tau=TAU,
        max_iter=40, q=Q, max_inner=8, inner="xla",
    )
    assert int(r.status) == Status.MAX_ITER  # genuinely mid-solve
    a = np.asarray(r.alpha, np.float32)
    assert 0 < (a > 0).sum() < Q

    K = np.asarray(rbf_cross(Xd, Xd, GAMMA), np.float32)
    y = np.asarray(Y, np.float32)
    f = K @ (a * y) - y
    active = np.asarray(
        i_high_mask(jnp.asarray(a), Yd, C, EPS)
        | i_low_mask(jnp.asarray(a), Yd, C, EPS)
    )
    assert active.any()
    return (
        jnp.asarray(K),
        jnp.asarray(y),
        jnp.asarray(a),
        jnp.asarray(f, jnp.float32),
        jnp.asarray(active),
    )


def _solve_pallas(args, wss):
    from tpusvm.ops.pallas.inner_smo import inner_smo_pallas

    K, y, a, f, active = args
    a_new, n_upd, progress, reason = inner_smo_pallas(
        K, y, a, f, active, C, EPS, TAU, max_inner=4096,
        interpret=False, wss=wss,  # compiled Mosaic, not interpret
    )
    return np.asarray(a_new), int(n_upd), bool(progress), int(reason)


def test_compiled_wss1_matches_xla_inner(midsolve_subproblem):
    K, y, a, f, active = midsolve_subproblem
    a_x, n_x, prog_x, reason_x = _inner_smo(
        K, y, a, f, active, C, EPS, TAU, 4096
    )
    a_x = np.asarray(a_x)
    a_p, n_p, prog_p, reason_p = _solve_pallas(midsolve_subproblem, wss=1)

    assert prog_p and prog_x
    assert reason_p == Status.CONVERGED
    assert int(reason_x) == Status.CONVERGED
    # same selection rule, same shared pair_update, both f32: the
    # trajectories should agree to accumulation noise
    np.testing.assert_allclose(a_p, a_x, atol=1e-3)
    # identical optima imply near-identical update counts
    assert abs(n_p - int(n_x)) <= max(5, int(n_x) // 10)


def test_compiled_wss2_reaches_same_optimum(midsolve_subproblem):
    K, y, a, f, active = midsolve_subproblem
    a_x, n_x, _, _ = _inner_smo(K, y, a, f, active, C, EPS, TAU, 4096)
    a_x = np.asarray(a_x)
    a_p, n_p, prog_p, reason_p = _solve_pallas(midsolve_subproblem, wss=2)

    assert prog_p
    assert reason_p == Status.CONVERGED
    assert n_p > 0
    # second-order partner selection: different trajectory, same convex
    # optimum (within the f32 noise band)
    np.testing.assert_allclose(a_p, a_x, atol=5e-3)


def test_compiled_box_constraints_and_padding(midsolve_subproblem):
    K, y, a, f, active = midsolve_subproblem
    a_p, _, _, _ = _solve_pallas(midsolve_subproblem, wss=1)
    assert (a_p >= -1e-6).all() and (a_p <= C + 1e-6).all()
    inactive = ~np.asarray(active)
    if inactive.any():
        # lanes outside the active set must come back untouched
        np.testing.assert_array_equal(
            a_p[inactive], np.asarray(a, np.float32)[inactive]
        )


def test_compiled_multirow_layout_matches_xla():
    """R > 1 (q=256) through compiled Mosaic: the sublane-packed (R, 128)
    layout must produce the same trajectory as the XLA inner loop. The
    module fixture's Q=128 is the degenerate single-row case; the bench
    configuration runs q=2048 (R=16), so a multi-row lowering regression
    would otherwise surface only in bench.py."""
    q = 256
    X, Y = rings(n=q, seed=11)
    Xs = MinMaxScaler().fit_transform(X).astype(np.float32)
    Xd = jnp.asarray(Xs)
    K = rbf_cross(Xd, Xd, GAMMA)
    y = jnp.asarray(np.asarray(Y, np.float32))
    a0 = jnp.zeros(q, jnp.float32)
    f0 = -y
    act = jnp.ones(q, bool)
    from tpusvm.ops.pallas.inner_smo import inner_smo_pallas

    a_x, n_x, _, _ = _inner_smo(K, y, a0, f0, act, C, EPS, TAU, 300)
    a_p, n_p, _, _ = inner_smo_pallas(
        K, y, a0, f0, act, C, EPS, TAU, max_inner=300, interpret=False
    )
    assert int(n_p) > 0
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_x), atol=1e-3)
    assert abs(int(n_p) - int(n_x)) <= max(5, int(n_x) // 10)


def test_compiled_fused_fupdate_matches_xla():
    """Compiled-Mosaic fused f-update contraction vs the XLA path —
    validates the MXU precision=HIGHEST distance dot and the VMEM-fused
    exp/matvec epilogue on real hardware."""
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    from tpusvm.ops.rbf import rbf_cross_matvec

    rng = np.random.default_rng(7)
    X = jnp.asarray(rng.random((1000, 784)), jnp.float32)
    XB = jnp.asarray(rng.random((256, 784)), jnp.float32)
    coef = jnp.asarray(rng.standard_normal(256), jnp.float32)
    got = np.asarray(rbf_cross_matvec_pallas(X, XB, coef, 0.00125,
                                             block=256, interpret=False))
    want = np.asarray(rbf_cross_matvec(X, XB, coef, 0.00125))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_compiled_fused_fupdate_under_x64():
    """Under jax_enable_x64 (the bench's f64-accumulator mode) grid index
    maps trace their integer returns as i64, which Mosaic cannot legalize
    ("func.return (i64)") — this killed the round-4 fused_on capture
    (benchmarks/results/tpu_capture_r4/fused_on.jsonl.err). The kernel now
    traces its pallas_call under jax.enable_x64(False); this test compiles
    and runs the fused contraction with x64 ON to pin the fix."""
    from tpusvm.ops.pallas.fused_fupdate import rbf_cross_matvec_pallas
    from tpusvm.ops.rbf import rbf_cross_matvec

    rng = np.random.default_rng(11)
    X = jnp.asarray(rng.random((500, 784)), jnp.float32)
    XB = jnp.asarray(rng.random((256, 784)), jnp.float32)
    coef = jnp.asarray(rng.standard_normal(256), jnp.float32)
    with jax.enable_x64(True):
        got = np.asarray(rbf_cross_matvec_pallas(X, XB, coef, 0.00125,
                                                 block=256, interpret=False))
    want = np.asarray(rbf_cross_matvec(X, XB, coef, 0.00125))
    np.testing.assert_allclose(got, want, atol=1e-4)
