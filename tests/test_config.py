"""Config helpers: accum-dtype sentinel resolution and preset integrity."""

import jax.numpy as jnp
import pytest

from tpusvm.config import SVMConfig, preset, resolve_accum_dtype


def test_resolve_accum_dtype_auto_is_f64():
    # the library default must resolve to the documented-good mixed-precision
    # configuration (f64 accumulators), matching the CLI's --accum default
    import jax

    assert resolve_accum_dtype("auto") == jnp.float64
    assert jax.config.jax_enable_x64


def test_resolve_accum_dtype_auto_flips_x64_with_warning():
    # conftest pre-enables x64 for the suite, so the actual flip branch
    # (enable + one-time UserWarning) needs a fresh interpreter
    import os
    import subprocess
    import sys

    code = (
        "import warnings\n"
        "from tpusvm.config import resolve_accum_dtype\n"
        "import jax\n"
        "assert not jax.config.jax_enable_x64\n"
        "with warnings.catch_warnings(record=True) as w:\n"
        "    warnings.simplefilter('always')\n"
        "    dt = resolve_accum_dtype('auto')\n"
        "    dt2 = resolve_accum_dtype('auto')\n"
        "assert jax.config.jax_enable_x64\n"
        "import jax.numpy as jnp\n"
        "assert dt == jnp.float64 and dt2 == jnp.float64\n"
        "x64w = [x for x in w if 'x64' in str(x.message)]\n"
        "assert len(x64w) == 1, x64w  # warns exactly at the flip\n"
        "print('OK')\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("JAX_ENABLE_X64", None)
    out = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


def test_resolve_accum_dtype_passthrough():
    assert resolve_accum_dtype(None) is None
    assert resolve_accum_dtype(jnp.float64) == jnp.float64
    assert resolve_accum_dtype(jnp.float32) == jnp.float32


def test_resolve_accum_dtype_rejects_unknown_string():
    with pytest.raises(ValueError, match="auto"):
        resolve_accum_dtype("float64")


def test_preset_reference_constants():
    cfg = preset("mnist")
    assert (cfg.C, cfg.gamma) == (10.0, 0.00125)
    assert cfg == SVMConfig()  # zero-flag run is a parity run
    with pytest.raises(ValueError, match="unknown preset"):
        preset("cifar")
