"""tpusvm.kernels: dispatch correctness, the linear fast path, Platt math,
and solver-level parity of every kernel family against the f64 oracle.

The RBF rows are the refactor's bit-transparency anchor: dispatch with
family="rbf" must return byte-identical arrays to the pre-refactor
ops/rbf.py calls (it IS those calls). Linear/poly are checked against
plain NumPy f64 references, and each family's full solve against
oracle.smo_train with the same config.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm import kernels
from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, blobs, rings
from tpusvm.kernels.platt import fit_platt, log_loss, platt_proba
from tpusvm.kernels.svr import collapse_duals, doubled_problem
from tpusvm.ops.rbf import rbf_cross, rbf_matvec, rbf_rows_at, sq_norms

jax.config.update("jax_enable_x64", True)


def _data(n=64, d=5, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.random((n, d)), jnp.float32)


# ---------------------------------------------------------------- dispatch
def test_validate_family():
    for fam in ("rbf", "linear", "poly", "sigmoid", "rff", "nystrom"):
        assert kernels.validate_family(fam) == fam
    with pytest.raises(ValueError, match="unknown kernel family"):
        kernels.validate_family("laplacian")


def test_needs_norms_only_rbf():
    assert kernels.needs_norms("rbf")
    assert not kernels.needs_norms("linear")
    assert not kernels.needs_norms("poly")


def test_rbf_dispatch_bit_identical_to_ops():
    X = _data()
    idx = jnp.asarray([3, 17], jnp.int32)
    sn = sq_norms(X)
    got = kernels.rows_at("rbf", X, idx, gamma=0.5, sn=sn)
    want = rbf_rows_at(X, idx, 0.5, sn)
    assert np.asarray(got).tobytes() == np.asarray(want).tobytes()
    got = kernels.cross("rbf", X, X[:8], gamma=0.5)
    want = rbf_cross(X, X[:8], 0.5)
    assert np.asarray(got).tobytes() == np.asarray(want).tobytes()
    coef = jnp.asarray(np.linspace(-1, 1, X.shape[0]), jnp.float32)
    got = kernels.matvec("rbf", X, coef, gamma=0.5)
    want = rbf_matvec(X, coef, 0.5)
    assert np.asarray(got).tobytes() == np.asarray(want).tobytes()


def test_linear_rows_and_cross_match_numpy():
    X = _data()
    Xf = np.asarray(X, np.float64)
    idx = jnp.asarray([0, 9], jnp.int32)
    got = np.asarray(kernels.rows_at("linear", X, idx, gamma=0.5))
    np.testing.assert_allclose(got, Xf[[0, 9]] @ Xf.T, rtol=1e-5)
    got = np.asarray(kernels.cross("linear", X, X[:8], gamma=0.5))
    np.testing.assert_allclose(got, Xf @ Xf[:8].T, rtol=1e-5)


@pytest.mark.parametrize("degree", [1, 2, 3])
def test_poly_values_match_numpy(degree):
    X = _data()
    Xf = np.asarray(X, np.float64)
    gamma, coef0 = 0.7, 1.3
    got = np.asarray(kernels.cross("poly", X, X[:8], gamma=gamma,
                                   coef0=coef0, degree=degree))
    want = (gamma * (Xf @ Xf[:8].T) + coef0) ** degree
    np.testing.assert_allclose(got, want, rtol=2e-5)


def test_linear_fast_and_generic_cross_matvec_agree():
    X = _data(200, 16)
    XB = _data(32, 16, seed=3)
    coef = jnp.asarray(np.random.default_rng(1).normal(size=32), jnp.float32)
    fast = np.asarray(kernels.cross_matvec("linear", X, XB, coef,
                                           gamma=0.0, fast=True))
    gen = np.asarray(kernels.cross_matvec("linear", X, XB, coef,
                                          gamma=0.0, fast=False, block=64))
    # association differs (primal collapse vs blocked K-row), so agreement
    # is to f32 matmul reordering noise, not bitwise
    np.testing.assert_allclose(fast, gen, rtol=1e-4, atol=1e-5)


def test_poly_cross_matvec_blocks_match_flat():
    X = _data(150, 8)
    XB = _data(16, 8, seed=5)
    coef = jnp.asarray(np.random.default_rng(2).normal(size=16), jnp.float32)
    blocked = np.asarray(kernels.cross_matvec(
        "poly", X, XB, coef, gamma=0.5, coef0=1.0, degree=2, block=64))
    flat = np.asarray(kernels.cross("poly", X, XB, gamma=0.5, coef0=1.0,
                                    degree=2) @ coef)
    np.testing.assert_allclose(blocked, flat, rtol=1e-4, atol=1e-5)


# ------------------------------------------------------------ svr doubling
def test_doubled_problem_layout():
    t = np.asarray([0.5, -1.0, 2.0])
    Y2, z = doubled_problem(t, 0.1)
    np.testing.assert_array_equal(Y2, [1, 1, 1, -1, -1, -1])
    np.testing.assert_allclose(z, [0.4, -1.1, 1.9, 0.6, -0.9, 2.1])


def test_doubled_problem_rejects_bad_input():
    with pytest.raises(ValueError, match="1-D"):
        doubled_problem(np.zeros((2, 2)), 0.1)
    with pytest.raises(ValueError, match="epsilon"):
        doubled_problem(np.zeros(3), -0.5)


def test_collapse_duals():
    beta = np.asarray([1.0, 0.0, 0.25, 0.5, 2.0, 0.25])
    np.testing.assert_allclose(collapse_duals(beta), [0.5, -2.0, 0.0])
    with pytest.raises(ValueError, match="2n"):
        collapse_duals(np.zeros(5))


# ------------------------------------------------------------------- platt
def test_platt_fit_recovers_signal_and_is_monotone():
    rng = np.random.default_rng(0)
    y = np.where(rng.random(600) < 0.5, 1, -1)
    f = y * rng.uniform(0.5, 2.0, 600) + rng.normal(0, 0.5, 600)
    A, B = fit_platt(f, y)
    assert A < 0  # informative scores fit a decreasing exp => increasing p
    grid = np.linspace(-6, 6, 101)
    p = platt_proba(grid, A, B)
    assert np.all(np.diff(p) > 0)
    assert log_loss(platt_proba(f, A, B), y) \
        < log_loss((f > 0).astype(float), y)


def test_platt_fit_handles_separable_scores():
    y = np.concatenate([np.ones(50), -np.ones(50)]).astype(np.int32)
    f = y * 3.0
    A, B = fit_platt(f, y)  # Bayes-shrunk targets keep this defined
    assert np.isfinite(A) and np.isfinite(B) and A < 0


def test_platt_fit_rejects_single_class():
    with pytest.raises(ValueError, match="both classes"):
        fit_platt(np.ones(10), np.ones(10))


def test_platt_proba_overflow_stable():
    p = platt_proba(np.asarray([-1e4, 1e4]), -5.0, 0.0)
    assert np.all(np.isfinite(p))
    assert p[0] < 1e-10 and p[1] > 1 - 1e-10


# -------------------------------------------- solver parity vs the oracle
def _parity(cfg, X, Y, targets=None, q=128):
    """Both solvers vs the f64 oracle at the cross-engine standard."""
    from tpusvm.oracle import get_sv_indices, smo_train
    from tpusvm.solver import smo_solve
    from tpusvm.solver.blocked import blocked_smo_solve

    o = smo_train(X, Y, cfg, targets=targets)
    assert o.status.name == "CONVERGED"
    tgt = None if targets is None else jnp.asarray(targets)
    common = dict(C=cfg.C, gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau,
                  kernel=cfg.kernel, degree=cfg.degree, coef0=cfg.coef0,
                  accum_dtype=jnp.float64, targets=tgt)
    pair = smo_solve(jnp.asarray(X, jnp.float64), jnp.asarray(Y), **common)
    blk = blocked_smo_solve(jnp.asarray(X, jnp.float32), jnp.asarray(Y),
                            q=q, **common)
    sv_o = set(get_sv_indices(o.alpha).tolist())
    # f64 features: exact SV-set match (the fuzz harness standard)
    assert set(get_sv_indices(np.asarray(pair.alpha)).tolist()) == sv_o
    assert abs(float(pair.b) - o.b) < 2e-3
    sv_b = set(get_sv_indices(np.asarray(blk.alpha)).tolist())
    assert len(sv_b ^ sv_o) <= max(2, len(sv_o) // 25)
    assert abs(float(blk.b) - o.b) < 2e-2
    return o


def test_linear_solvers_match_oracle():
    X, Y = blobs(n=220, d=6, seed=11)
    Xs = MinMaxScaler().fit_transform(X)
    _parity(SVMConfig(C=1.0, kernel="linear"), Xs, Y)


def test_poly_solvers_match_oracle():
    X, Y = rings(n=220, seed=12)
    Xs = MinMaxScaler().fit_transform(X)
    _parity(SVMConfig(C=10.0, gamma=1.0, kernel="poly", degree=3,
                      coef0=1.0), Xs, Y)


def test_linear_generic_path_reaches_same_solution():
    from tpusvm.oracle import get_sv_indices
    from tpusvm.solver.blocked import blocked_smo_solve

    X, Y = blobs(n=220, d=6, seed=13)
    Xs = MinMaxScaler().fit_transform(X)
    res = {}
    for fast in (True, False):
        r = blocked_smo_solve(jnp.asarray(Xs, jnp.float32), jnp.asarray(Y),
                              q=128, C=1.0, kernel="linear",
                              kernel_fast=fast, accum_dtype=jnp.float64)
        assert int(r.status) == 1  # CONVERGED
        res[fast] = (set(get_sv_indices(np.asarray(r.alpha)).tolist()),
                     float(r.b))
    assert len(res[True][0] ^ res[False][0]) <= 2
    assert abs(res[True][1] - res[False][1]) < 2e-3


def test_fused_fupdate_true_rejected_off_rbf():
    from tpusvm.solver.blocked import blocked_smo_solve

    X, Y = blobs(n=64, d=4, seed=1)
    with pytest.raises(ValueError, match="RBF pipeline only"):
        blocked_smo_solve(jnp.asarray(X, jnp.float32), jnp.asarray(Y),
                          kernel="linear", fused_fupdate=True)


def test_solver_rejects_unknown_family():
    from tpusvm.solver import smo_solve

    X, Y = blobs(n=32, d=3, seed=1)
    with pytest.raises(ValueError, match="unknown kernel family"):
        smo_solve(jnp.asarray(X, jnp.float32), jnp.asarray(Y),
                  kernel="laplacian")


def test_config_validates_kernel_fields():
    with pytest.raises(ValueError, match="unknown kernel family"):
        SVMConfig(kernel="tanh")
    with pytest.raises(ValueError, match="degree"):
        SVMConfig(degree=0)
    with pytest.raises(ValueError, match="epsilon"):
        SVMConfig(epsilon=-0.1)
