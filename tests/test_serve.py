"""tpusvm.serve tests: bit-identity under concurrency, batching mechanics,
backpressure/timeouts, compile-cache accounting, metrics, HTTP frontend.

The serving contract under test (ISSUE 2 acceptance): concurrent
micro-batched submissions return scores BIT-IDENTICAL to direct
decision_function calls on the same rows, with zero errors, zero
post-warm-up recompiles, and at most len(buckets) compiled shapes."""

import json
import threading
import time
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import rings
from tpusvm.models import BinarySVC, OneVsRestSVC
from tpusvm.serve import (
    MicroBatcher,
    Metrics,
    ServeConfig,
    Server,
    bucket_for,
    default_buckets,
)
from tpusvm.status import ServeStatus

CFG = SVMConfig(C=10.0, gamma=10.0)


@pytest.fixture(scope="module")
def binary_model():
    X, Y = rings(n=300, seed=2)
    return BinarySVC(CFG, dtype=jnp.float64).fit(X, Y)


def _four_class_data(n=300, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0, 0], [6, 0], [0, 6], [6, 6]], float)
    labels = rng.integers(0, 4, n)
    X = centers[labels] + rng.normal(0, 0.8, (n, 2))
    return X, labels.astype(np.int32)


# ---------------------------------------------------------------- buckets
def test_default_buckets_and_lookup():
    assert default_buckets(8) == (1, 2, 4, 8)
    assert default_buckets(1) == (1,)
    # non-power-of-two cap: last bucket IS the cap
    assert default_buckets(12) == (1, 2, 4, 8, 12)
    assert bucket_for(1, (1, 2, 4, 8)) == 1
    assert bucket_for(3, (1, 2, 4, 8)) == 4
    assert bucket_for(8, (1, 2, 4, 8)) == 8
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        bucket_for(9, (1, 2, 4, 8))
    with pytest.raises(ValueError, match="max_batch"):
        default_buckets(0)


def test_serve_config_rejects_uncovering_buckets():
    with pytest.raises(ValueError, match="do not cover"):
        ServeConfig(max_batch=16, buckets=(1, 2, 4)).resolved_buckets()


# ----------------------------------------------------- bit-identity + load
def test_concurrent_submits_bit_identical_and_compile_free(binary_model):
    """The acceptance-criteria core: >= 8 client threads of single-row
    submits come back bit-identical to model.decision_function, with zero
    errors and zero post-warm-up recompiles, and the compile cache holds
    at most len(buckets) shapes."""
    Xt, _ = rings(n=64, seed=3)
    ref = binary_model.decision_function(Xt)
    ref_labels = binary_model.predict(Xt)
    with Server(ServeConfig(max_batch=8, max_delay_ms=2.0),
                dtype=jnp.float64) as srv:
        srv.add_model("rings", binary_model)
        # bucket 1 floors to 2 (the m == 1 dot program is the one CPU
        # geometry with contraction-order drift — see serve/buckets.py)
        assert srv.status()["models"]["rings"]["buckets"] == [2, 4, 8]
        compiled = srv.warmup()["rings"]
        assert compiled == 3
        # idempotent: a second warm-up builds nothing
        assert srv.warmup()["rings"] == 0

        n_threads, per_thread = 8, 24
        results = {}

        def client(t):
            out = []
            for i in range(per_thread):
                out.append(srv.submit("rings", Xt[(t * per_thread + i) % 64]))
            results[t] = out

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        for t, out in results.items():
            for i, r in enumerate(out):
                idx = (t * per_thread + i) % 64
                assert r.ok, r.status
                assert r.scores == ref[idx]          # bitwise
                assert r.label == ref_labels[idx]
        snap = srv.metrics("rings")
        st = srv.status()["models"]["rings"]
        assert snap["errors"] == 0 and snap["timeouts"] == 0
        assert snap["recompiles"] == 0
        assert snap["ok"] == n_threads * per_thread
        assert st["compiled_shapes"] <= len(st["buckets"])
        assert snap["latency_s"]["p50"] is not None


def test_submit_many_coalesces_and_matches_direct(binary_model):
    Xt, _ = rings(n=40, seed=4)
    ref = binary_model.decision_function(Xt)
    with Server(ServeConfig(max_batch=8), dtype=jnp.float64) as srv:
        srv.add_model("rings", binary_model)
        srv.warmup()
        res = srv.submit_many("rings", Xt)
        assert all(r.ok for r in res)
        got = np.array([r.scores for r in res])
        np.testing.assert_array_equal(got, ref)
        # 40 rows through an 8-cap batcher: at least 5 flushes, and the
        # mean occupancy must show real coalescing (not 1 row/batch)
        snap = srv.metrics("rings")
        assert snap["batches"] >= 5
        assert snap["mean_batch_rows"] > 1.5
        # the direct path agrees bitwise too (the benchmark baseline)
        scores, labels = srv.predict_direct("rings", Xt)
        np.testing.assert_array_equal(scores, ref)
        np.testing.assert_array_equal(labels, binary_model.predict(Xt))


def test_ovr_serving_matches_direct():
    """OVR bit-identity holds on the multiple-of-4 row grid: the class-
    score gemm dispatches to a different CPU dot kernel below 4 rows
    (~1 ulp contraction-order drift), so the compile cache floors OVR
    buckets at 4 and every power-of-two bucket is geometry-invariant —
    served scores match a direct call with a multiple-of-4 row count
    bitwise."""
    X, labels = _four_class_data(n=300, seed=0)
    m = OneVsRestSVC(SVMConfig(C=10.0, gamma=2.0), dtype=jnp.float64).fit(
        X, labels)
    Xq, _ = _four_class_data(n=32, seed=1)
    ref_scores = m.decision_function(Xq)
    ref_labels = m.predict(Xq)
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        srv.add_model("digits", m)
        st = srv.status()["models"]["digits"]
        assert st["buckets"] == [4]  # 1/2 floored away for OVR
        srv.warmup()
        res = srv.submit_many("digits", Xq)
        assert all(r.ok for r in res)
        np.testing.assert_array_equal(
            np.stack([r.scores for r in res]), ref_scores)
        np.testing.assert_array_equal(
            np.array([r.label for r in res]), ref_labels)
        # single-row submits run through the same floored bucket, so they
        # agree with the batch path bitwise
        one = srv.submit("digits", Xq[0])
        assert one.ok and (one.scores == ref_scores[0]).all()
        assert one.label == ref_labels[0]


def test_unwarmed_server_counts_no_recompiles_but_compiles_lazily(binary_model):
    """Without warm-up the first request per bucket compiles on demand;
    those compiles are NOT recompiles (warm-up never ran), and a
    subsequent warm-up only fills the buckets not yet hit."""
    Xt, _ = rings(n=8, seed=5)
    with Server(ServeConfig(max_batch=8), dtype=jnp.float64) as srv:
        srv.add_model("rings", binary_model)
        r = srv.submit("rings", Xt[0])
        assert r.ok
        st = srv.status()["models"]["rings"]
        assert st["compiles"] >= 1 and st["recompiles"] == 0
        assert not st["warmed"]
        filled = srv.warmup()["rings"]
        assert filled == len(st["buckets"]) - st["compiled_shapes"]


# ------------------------------------------------- backpressure / deadlines
def _slow_run_batch(delay_s):
    def run(X):
        time.sleep(delay_s)
        scores = np.zeros(X.shape[0])
        return scores, np.ones(X.shape[0], np.int32)
    return run


def test_queue_full_fast_fails():
    metrics = Metrics(buckets=(1,))
    b = MicroBatcher(_slow_run_batch(0.2), max_batch=1, max_delay_s=0.0,
                     queue_size=2, timeout_s=5.0, metrics=metrics)
    try:
        row = np.zeros(2)
        results = []
        done = threading.Event()

        def fire():
            results.append(b.submit(row))
            done.set()

        # one in-flight request occupies the worker; then overfill the queue
        t = threading.Thread(target=fire)
        t.start()
        time.sleep(0.05)  # worker is now sleeping inside run_batch
        statuses = []
        threads = []
        lock = threading.Lock()

        def enqueue():
            r = b.submit(row)
            with lock:
                statuses.append(r.status)

        for _ in range(6):
            th = threading.Thread(target=enqueue)
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        t.join()
        assert ServeStatus.QUEUE_FULL in statuses  # backpressure engaged
        assert metrics.snapshot()["queue_full"] >= 1
        # fast-fail means the rejected calls returned well before the
        # worker could have served them
        assert done.is_set()
    finally:
        b.close()


def test_request_timeout_and_dead_on_arrival_drop():
    metrics = Metrics(buckets=(1, 2, 4))
    calls = []

    def run(X):
        calls.append(X.shape[0])
        time.sleep(0.15)
        return np.zeros(X.shape[0]), np.ones(X.shape[0], np.int32)

    b = MicroBatcher(run, max_batch=4, max_delay_s=0.0, queue_size=16,
                     timeout_s=0.05, metrics=metrics)
    try:
        row = np.zeros(2)
        # first request occupies the worker for 0.15s; the second expires
        # while queued (deadline 0.05s) and must come back TIMEOUT without
        # the worker ever scoring it
        r1_holder = []
        t = threading.Thread(target=lambda: r1_holder.append(b.submit(row)))
        t.start()
        time.sleep(0.03)
        r2 = b.submit(row)
        t.join()
        assert r1_holder[0].status == ServeStatus.TIMEOUT  # 0.15 > 0.05
        assert r2.status == ServeStatus.TIMEOUT
        time.sleep(0.3)  # let the worker drain the dead request
        assert metrics.snapshot()["timeouts"] == 2
        # the dead-on-arrival request was dropped, not scored: only the
        # first ever reached run_batch
        assert sum(calls) == 1
    finally:
        b.close()


def test_closed_batcher_returns_shutdown():
    b = MicroBatcher(_slow_run_batch(0.0), max_batch=2, max_delay_s=0.0,
                     queue_size=4)
    b.close()
    r = b.submit(np.zeros(2))
    assert r.status == ServeStatus.SHUTDOWN


def test_server_close_under_concurrent_submit_load(binary_model):
    """ISSUE 7 satellite: close() racing a storm of concurrent submits
    must leave every request with a clean terminal status (scored,
    SHUTDOWN, or a timeout) — no deadlocked clients, no silently dropped
    futures — and finish promptly."""
    Xt, _ = rings(n=16, seed=7)
    srv = Server(ServeConfig(max_batch=4, max_delay_ms=1.0,
                             timeout_ms=2000.0), dtype=jnp.float64)
    srv.add_model("rings", binary_model)
    srv.warmup()

    n_threads, per_thread = 8, 40
    results = [[] for _ in range(n_threads)]
    start = threading.Barrier(n_threads + 1)

    def client(t):
        start.wait()
        for i in range(per_thread):
            results[t].append(srv.submit("rings", Xt[i % 16]))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    start.wait()
    time.sleep(0.02)  # let the storm build
    t0 = time.monotonic()
    srv.close()
    close_s = time.monotonic() - t0
    for t in threads:
        t.join(timeout=10.0)
    assert not any(t.is_alive() for t in threads), "client deadlocked"
    assert close_s < 6.0
    flat = [r for chunk in results for r in chunk]
    assert len(flat) == n_threads * per_thread  # nobody dropped
    allowed = {ServeStatus.OK, ServeStatus.SHUTDOWN, ServeStatus.TIMEOUT,
               ServeStatus.QUEUE_FULL}
    assert {r.status for r in flat} <= allowed
    # the race hit both sides: some requests scored, some saw shutdown
    assert any(r.ok for r in flat)
    assert any(r.status == ServeStatus.SHUTDOWN for r in flat)


def test_scoring_error_fails_requests_not_worker():
    metrics = Metrics(buckets=(1, 2))
    state = {"boom": True}

    def run(X):
        if state["boom"]:
            raise RuntimeError("kernel exploded")
        return np.zeros(X.shape[0]), np.ones(X.shape[0], np.int32)

    b = MicroBatcher(run, max_batch=2, max_delay_s=0.0, queue_size=8,
                     timeout_s=1.0, metrics=metrics)
    try:
        r = b.submit(np.zeros(2))
        assert r.status == ServeStatus.ERROR
        assert metrics.snapshot()["errors"] == 1
        # the worker survived the exception and keeps serving
        state["boom"] = False
        r2 = b.submit(np.zeros(2))
        assert r2.ok
    finally:
        b.close()


# ----------------------------------------------------------------- guards
def test_submit_validates_rows(binary_model):
    with Server(ServeConfig(max_batch=2), dtype=jnp.float64) as srv:
        srv.add_model("rings", binary_model)
        with pytest.raises(ValueError, match="features"):
            srv.submit("rings", np.zeros(5))
        with pytest.raises(ValueError, match="one row"):
            srv.submit("rings", np.zeros((3, 2)))
        with pytest.raises(KeyError, match="unknown model"):
            srv.submit("nope", np.zeros(2))
        with pytest.raises(ValueError, match="already registered"):
            srv.add_model("rings", binary_model)


# ---------------------------------------------------------------- metrics
def test_metrics_snapshot_and_text():
    m = Metrics(buckets=(1, 2, 4))
    m.inc("requests", 3)
    m.inc("ok", 2)
    m.observe_batch(2, 2)
    m.observe_batch(4, 3)
    for v in (0.001, 0.002, 0.003):
        m.observe_latency(v)
    snap = m.snapshot()
    assert snap["requests"] == 3 and snap["ok"] == 2
    assert snap["batch_occupancy"]["2"]["batches"] == 1
    assert snap["batch_occupancy"]["4"]["mean_rows"] == 3.0
    assert snap["mean_batch_rows"] == 2.5
    assert snap["latency_s"]["p50"] == 0.002
    assert snap["latency_s"]["p99"] == 0.003
    json.dumps(snap)  # JSON-able end to end
    text = m.render_text(labels='model="m"')
    assert 'tpusvm_serve_requests_total{model="m"} 3' in text
    assert 'bucket="4"' in text and 'quantile="50"' in text


# ------------------------------------------------------------------- HTTP
def test_http_endpoint_roundtrip(binary_model):
    from tpusvm.serve.http import make_http_server, start_http_thread

    Xt, _ = rings(n=10, seed=6)
    ref_scores = binary_model.decision_function(Xt)
    ref_labels = binary_model.predict(Xt)
    with Server(ServeConfig(max_batch=8), dtype=jnp.float64) as srv:
        srv.add_model("rings", binary_model)
        srv.warmup()
        httpd = make_http_server(srv, port=0)  # ephemeral port
        start_http_thread(httpd)
        try:
            port = httpd.server_address[1]
            base = f"http://127.0.0.1:{port}"

            body = json.dumps({"instances": Xt.tolist()}).encode()
            req = urllib.request.Request(
                f"{base}/v1/models/rings:predict", data=body,
                headers={"Content-Type": "application/json"})
            resp = json.loads(urllib.request.urlopen(req).read())
            assert resp["statuses"] == ["OK"] * 10
            np.testing.assert_array_equal(
                np.asarray(resp["scores"]), ref_scores)
            np.testing.assert_array_equal(
                np.asarray(resp["predictions"]), ref_labels)

            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz").read())
            assert health["status"] == "ok"
            assert health["models"] == {"rings": "closed"}
            text = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert 'tpusvm_serve_ok_total{model="rings"} 10' in text
            models = json.loads(
                urllib.request.urlopen(f"{base}/v1/models").read())
            assert models["models"]["rings"]["recompiles"] == 0
            mjson = json.loads(urllib.request.urlopen(
                f"{base}/v1/models/rings/metrics").read())
            assert mjson["ok"] == 10

            # unknown model -> 404; malformed body -> 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/models/nope:predict", data=body,
                    headers={"Content-Type": "application/json"}))
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"{base}/v1/models/rings:predict", data=b"not json",
                    headers={"Content-Type": "application/json"}))
            assert ei.value.code == 400
        finally:
            httpd.shutdown()


# -------------------------------------------------- kernel/task matrix
def test_svr_serving_matches_direct_estimator(tmp_path):
    """SVR models serve predicted VALUES — bit-identical to the direct
    estimator call (same scaler arithmetic, same bucket executables)."""
    from tpusvm.data import svr_sine
    from tpusvm.models import EpsilonSVR

    X, t = svr_sine(n=200, d=1, noise=0.05, seed=3)
    model = EpsilonSVR(SVMConfig(C=10.0, gamma=20.0, epsilon=0.1)).fit(X, t)
    Xq, _ = svr_sine(n=12, d=1, noise=0.05, seed=44)
    # pad to the serve floor geometry: direct calls at multiples of 2 rows
    ref = model.predict(Xq)
    p = str(tmp_path / "svr.npz")
    model.save(p)
    with Server(ServeConfig(max_batch=8)) as srv:
        entry = srv.load_model("svr", p)
        assert entry.kind == "svr"
        srv.warmup()
        results = srv.submit_many("svr", Xq)
        assert all(r.ok for r in results)
        served = np.asarray([float(r.label) for r in results])
        np.testing.assert_array_equal(served, np.asarray(ref, served.dtype))
        scores, labels = srv.predict_direct("svr", Xq)
        np.testing.assert_array_equal(scores, labels)  # value IS the score
        assert srv.metrics("svr")["recompiles"] == 0


def test_poly_model_serves_through_kernel_routed_executable():
    from tpusvm.data import blobs

    X, Y = blobs(n=200, d=4, seed=5)
    model = BinarySVC(SVMConfig(C=1.0, gamma=1.0, kernel="poly",
                                degree=2, coef0=1.0),
                      dtype=jnp.float64).fit(X, Y)
    Xq = X[:8]
    with Server(ServeConfig(max_batch=8), dtype=jnp.float64) as srv:
        srv.add_model("poly", model)
        srv.warmup()
        scores, labels = srv.predict_direct("poly", Xq)
        np.testing.assert_array_equal(scores,
                                      model.decision_function(Xq))
        assert srv.status()["models"]["poly"]["kernel"] == "poly"


def test_http_proba_field_matches_predict_proba(tmp_path):
    """Calibrated binary model over HTTP gains a proba field, bit-equal
    to the offline predict_proba on the same rows; uncalibrated models
    serve no such field."""
    import urllib.error  # noqa: F401  (match the module's other tests)
    from tpusvm.serve.http import make_http_server, start_http_thread

    X, Y = rings(n=240, seed=1)
    cal = BinarySVC(SVMConfig(C=10.0, gamma=10.0), dtype=jnp.float64)
    cal.fit(X, Y)
    cal.calibrate(X, Y, folds=2, seed=0)
    plain = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float64).fit(X, Y)
    Xq, _ = rings(n=6, seed=8)
    ref = cal.predict_proba(Xq)[:, 1]
    with Server(ServeConfig(max_batch=8), dtype=jnp.float64) as srv:
        srv.add_model("cal", cal)
        srv.add_model("plain", plain)
        srv.warmup()
        httpd = make_http_server(srv, port=0)
        start_http_thread(httpd)
        try:
            port = httpd.server_address[1]
            body = json.dumps({"instances": Xq.tolist()}).encode()

            def post(name):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/models/{name}:predict",
                    data=body,
                    headers={"Content-Type": "application/json"})
                return json.loads(urllib.request.urlopen(req).read())

            resp = post("cal")
            assert "proba" in resp
            np.testing.assert_array_equal(np.asarray(resp["proba"]), ref)
            assert all(0.0 <= p <= 1.0 for p in resp["proba"])
            assert "proba" not in post("plain")
        finally:
            httpd.shutdown()
    assert srv.status()["models"]["cal"]["calibrated"] is True
    assert srv.status()["models"]["plain"]["calibrated"] is False


# -------------------------------------------------------------------- CLI
def test_cli_serve_smoke(tmp_path, capsys, binary_model):
    from tpusvm.cli import main

    p = str(tmp_path / "m.npz")
    binary_model.save(p)
    rc = main(["serve", "--model", f"rings={p}", "--smoke",
               "--max-batch", "8", "--smoke-threads", "4",
               "--smoke-requests", "8", "--dtype", "float64"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "loaded rings: binary" in out
    assert "warmed rings: 3 bucket executables compiled" in out
    assert "0 errors, 0 recompiles" in out
    assert 'tpusvm_serve_ok_total{model="rings"} 32' in out


@pytest.mark.slow
def test_batched_throughput_beats_sequential():
    """The acceptance throughput bar (>= 3x sequential under >= 8 client
    threads). Timing-sensitive, so tier-2; tier-1 proves the mechanism
    (coalescing occupancy > 1) without wall-clock assertions.

    Needs a realistically-sized model: micro-batching pays when per-row
    kernel work dominates per-request dispatch overhead, so a toy 2-D
    rings model (exec ~50us) measures Python overhead, not batching. An
    MNIST-shaped model (~700 SVs x 784 features) measures 3.9-5x here."""
    from tpusvm.data.synthetic import (
        BENCH_LABEL_NOISE,
        BENCH_NOISE,
        mnist_like,
    )
    from tpusvm.serve.server import sequential_qps

    X, Y = mnist_like(n=4160, d=784, seed=587, noise=BENCH_NOISE,
                      label_noise=BENCH_LABEL_NOISE)
    model = BinarySVC(SVMConfig(C=10.0, gamma=0.00125),
                      dtype=jnp.float32).fit(X[:4096], Y[:4096])
    Xt = X[4096:4160]
    with Server(ServeConfig(max_batch=16, max_delay_ms=1.0),
                dtype=jnp.float32) as srv:
        srv.add_model("mnist", model)
        srv.warmup()
        seq = sequential_qps(srv, "mnist", list(Xt), duration_s=1.0)

        counts = [0] * 8
        stop = time.monotonic() + 1.0

        def client(t):
            i = 0
            while time.monotonic() < stop:
                assert srv.submit("mnist", Xt[i % 64]).ok
                counts[t] += 1
                i += 1

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        batched = sum(counts) / 1.0
        assert batched >= 3 * seq, (batched, seq)
        assert srv.metrics("mnist")["recompiles"] == 0
