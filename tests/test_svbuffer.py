"""SVBuffer unit tests: compaction, dedup-by-ID, cascade merge semantics."""

import jax.numpy as jnp
import numpy as np

from tpusvm.parallel import svbuffer as sb


def _buf(ids, alpha=None, valid=None, y=None, d=2):
    ids = np.asarray(ids, np.int32)
    n = len(ids)
    X = np.stack([ids.astype(float), np.arange(n, dtype=float)], axis=1)
    return sb.SVBuffer(
        X=jnp.asarray(X),
        Y=jnp.asarray(y if y is not None else np.ones(n, np.int32)),
        alpha=jnp.asarray(alpha if alpha is not None else np.zeros(n)),
        ids=jnp.asarray(ids),
        valid=jnp.asarray(valid if valid is not None else np.ones(n, bool)),
    )


def test_compact_stable_order_and_count():
    buf = _buf([5, 7, 9, 11], valid=[False, True, False, True])
    out, count = sb.compact(buf, 4)
    assert int(count) == 2
    np.testing.assert_array_equal(np.asarray(out.ids)[:2], [7, 11])
    assert not np.asarray(out.valid)[2:].any()


def test_compact_overflow_drops_tail_but_reports_count():
    buf = _buf([1, 2, 3])
    out, count = sb.compact(buf, 2)
    assert int(count) == 3  # pre-truncation count lets callers detect overflow
    np.testing.assert_array_equal(np.asarray(out.ids), [1, 2])


def test_dedup_keeps_first_occurrence():
    # insert-if-new semantics of the reference's unordered_set loop
    buf = _buf([4, 8, 4, 8, 2], alpha=[0.1, 0.2, 0.3, 0.4, 0.5])
    out = sb.dedup_first(buf)
    v = np.asarray(out.valid)
    np.testing.assert_array_equal(v, [True, True, False, False, True])


def test_merge_dedup_cascade_alpha_semantics():
    # primary keeps alpha (warm start); secondary alphas reset to 0; secondary
    # rows whose id is already present are dropped (mpi_svm_main2.cpp:481-502)
    primary = _buf([10, 20], alpha=[0.5, 0.7])
    secondary = _buf([20, 30, 10, 40], alpha=[9.0, 9.0, 9.0, 9.0])
    merged, count = sb.merge_dedup(primary, secondary, 6)
    ids = np.asarray(merged.ids)
    al = np.asarray(merged.alpha)
    valid = np.asarray(merged.valid)
    assert int(count) == 4
    np.testing.assert_array_equal(ids[:4], [10, 20, 30, 40])
    np.testing.assert_allclose(al[:4], [0.5, 0.7, 0.0, 0.0])
    assert valid[:4].all() and not valid[4:].any()


def test_merge_dedup_duplicates_within_secondary():
    # dup ids across two workers' SV sets: first occurrence wins
    primary = _buf([], d=2)
    secondary = _buf([3, 5, 3, 5, 3])
    merged, count = sb.merge_dedup(primary, secondary, 8)
    assert int(count) == 2
    np.testing.assert_array_equal(np.asarray(merged.ids)[:2], [3, 5])


def test_extract_svs_threshold():
    train = _buf([1, 2, 3, 4], valid=[True, True, True, False])
    alpha = jnp.asarray([0.5, 1e-9, 0.2, 0.9])  # last is padding: excluded
    out, count = sb.extract_svs(train, alpha, 1e-8, 4)
    assert int(count) == 2
    np.testing.assert_array_equal(np.asarray(out.ids)[:2], [1, 3])
    np.testing.assert_allclose(np.asarray(out.alpha)[:2], [0.5, 0.2])
