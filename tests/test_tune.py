"""tpusvm.tune: folds, grid geometry, warm seeding, search driver, results.

The subsystem's correctness contract has three legs:
  - splits are deterministic, stratified, and exhaustive (every row in
    exactly one validation side);
  - warm seeding never changes WHAT a sweep decides — winner and CV
    accuracies match a cold sweep of the same grid/folds (the benchmark
    gate asserts this at full size; here at test size);
  - the persisted artifact is format-versioned and fails loudly on
    foreign/tampered files, like model serialization.
"""

import json

import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import rings
from tpusvm.status import TuneStatus
from tpusvm.tune import (
    TuneConfig,
    format_table,
    is_tune_result,
    load_tune_result,
    make_grid,
    save_tune_result,
    stratified_kfold,
    tune,
)
from tpusvm.tune.grid import log_distance, log_grid, nearest_point
from tpusvm.tune.search import _rung_sizes
from tpusvm.tune.warm import WarmStore, feasible_seed


# ------------------------------------------------------------------- folds
def test_stratified_kfold_exhaustive_and_deterministic():
    Y = np.array([1] * 30 + [-1] * 18, np.int32)
    folds = stratified_kfold(Y, 3, seed=5)
    all_val = np.concatenate([f.val_idx for f in folds])
    # every row in exactly one val side
    np.testing.assert_array_equal(np.sort(all_val), np.arange(48))
    for f in folds:
        assert len(np.intersect1d(f.train_idx, f.val_idx)) == 0
        # stratified: each val side carries both classes at ~global ratio
        yv = Y[f.val_idx]
        assert (yv == 1).sum() == 10 and (yv == -1).sum() == 6
    folds2 = stratified_kfold(Y, 3, seed=5)
    for a, b in zip(folds, folds2):
        np.testing.assert_array_equal(a.train_idx, b.train_idx)
        np.testing.assert_array_equal(a.val_idx, b.val_idx)


def test_stratified_kfold_train_order_is_shuffled():
    # rung subsets are PREFIXES of train_idx, so its order must mix
    # classes — sorted order would make small rungs echo the storage order
    Y = np.array([1] * 40 + [-1] * 40, np.int32)  # label-sorted input
    (f, *_) = stratified_kfold(Y, 4, seed=0)
    prefix = Y[f.train_idx[:16]]
    assert (prefix == 1).any() and (prefix == -1).any()
    assert not np.all(np.diff(f.train_idx) > 0)


def test_stratified_kfold_rejects_starved_class():
    Y = np.array([1] * 20 + [-1] * 2, np.int32)
    with pytest.raises(ValueError, match="class .* rows < k"):
        stratified_kfold(Y, 3)
    with pytest.raises(ValueError, match="2 <= k"):
        stratified_kfold(np.ones(8, np.int32), 1)


# -------------------------------------------------------------------- grid
def test_grid_snake_order_adjacent_steps():
    g = make_grid([1.0, 4.0, 16.0], [0.5, 2.0, 8.0])
    pts = g.points()
    assert len(pts) == 9 and g.shape == (3, 3)
    # consecutive points differ in exactly one coordinate by one grid step
    for a, b in zip(pts, pts[1:]):
        changed = (a[0] != b[0]) + (a[1] != b[1])
        assert changed == 1
        assert log_distance(a, b) <= np.log(4.0) + 1e-9


def test_grid_validation():
    with pytest.raises(ValueError, match="positive"):
        make_grid([1.0, -2.0], [0.5])
    with pytest.raises(ValueError, match="distinct"):
        make_grid([1.0, 1.0], [0.5])
    with pytest.raises(ValueError, match="at least one"):
        make_grid([], [0.5])
    lg = log_grid(10.0, 0.001, span=1, step=4.0)
    assert lg.shape == (3, 3)
    assert 10.0 in lg.C_values and 0.001 in lg.gamma_values


def test_nearest_point_log_space_ties_to_earliest():
    cands = [(1.0, 1.0), (4.0, 1.0), (1.0, 4.0)]
    assert nearest_point((2.0, 1.0), cands) == 0  # log-closer to (1,1)
    assert nearest_point((4.0, 4.0), cands) == 1  # tie -> earliest


def test_rung_sizes():
    assert _rung_sizes(1000, 100, 3) == [100, 300, 900, 1000]
    assert _rung_sizes(100, 100, 3) == [100]
    assert _rung_sizes(50, 100, 3) == [50]


# -------------------------------------------------------------------- warm
def test_feasible_seed_clips_and_balances():
    Y = np.array([1, 1, -1, -1], np.int32)
    a = feasible_seed(np.array([5.0, 3.0, 2.0, 1.0]), Y, C=2.0)
    assert (a >= 0).all() and (a <= 2.0).all()  # clipped into the new box
    np.testing.assert_allclose((a * Y).sum(), 0.0, atol=1e-12)
    # the lighter side is untouched, the heavier side scaled down
    np.testing.assert_allclose(a[2:], [2.0, 1.0])


def test_feasible_seed_one_sided_collapses_to_zero():
    Y = np.array([1, 1, -1], np.int32)
    a = feasible_seed(np.array([1.0, 2.0, 0.0]), Y, C=10.0)
    assert (a == 0).all()


def test_warm_store_prefers_same_point_then_neighbour():
    Y = np.array([1, -1, 1, -1], np.int32)
    store = WarmStore()
    assert store.seed(0, (1.0, 1.0), 4, Y, C=10.0) is None  # empty: cold
    store.record(0, (1.0, 1.0), np.array([1.0, 1.0, 0.0, 0.0]))
    store.record(0, (100.0, 100.0), np.array([0.0, 0.0, 2.0, 2.0]))
    # same point wins over any neighbour, zero-padding across rung sizes
    s = store.seed(0, (1.0, 1.0), 6,
                   np.array([1, -1, 1, -1, 1, -1], np.int32), C=10.0)
    np.testing.assert_allclose(s, [1.0, 1.0, 0, 0, 0, 0])
    # unseen point: the log-space-nearest donor's alphas
    s2 = store.seed(0, (2.0, 2.0), 4, Y, C=10.0)
    np.testing.assert_allclose(s2, [1.0, 1.0, 0.0, 0.0])
    # folds are independent stores
    assert store.seed(1, (1.0, 1.0), 4, Y, C=10.0) is None


# ------------------------------------------------------------------ search
@pytest.fixture(scope="module")
def rings_data():
    return rings(n=240, noise=0.25, seed=3)


def _cfg(**kw):
    kw.setdefault("folds", 2)
    kw.setdefault("seed", 1)
    return TuneConfig(**kw)


def test_tune_grid_warm_matches_cold_decision(rings_data):
    X, Y = rings_data
    grid = make_grid([1.0, 4.0], [1.0, 4.0])
    warm = tune(X, Y, grid, _cfg(warm_start=True))
    cold = tune(X, Y, grid, _cfg(warm_start=False))
    assert warm.winner["C"] == cold.winner["C"]
    assert warm.winner["gamma"] == cold.winner["gamma"]
    for pw, pc in zip(warm.points, cold.points):
        assert pw["status"] == TuneStatus.EVALUATED.name
        assert abs(pw["cv_accuracy"] - pc["cv_accuracy"]) <= 1e-6
    # warm seeding actually engaged everywhere after the first point
    assert all(p["warm_seeded"] == 2 for p in warm.points[1:])
    assert all(p["warm_seeded"] == 0 for p in cold.points)
    assert warm.total_updates == sum(p["n_updates"] for p in warm.points)
    assert warm.n == 240 and warm.d == 2 and warm.folds == 2


def test_tune_halving_prunes_and_promotes(rings_data):
    X, Y = rings_data
    grid = make_grid([0.5, 2.0, 8.0], [0.5, 2.0, 8.0])
    res = tune(X, Y, grid, _cfg(schedule="halving", min_rung=32, eta=3))
    statuses = [p["status"] for p in res.points]
    n_eval = statuses.count(TuneStatus.EVALUATED.name)
    n_pruned = statuses.count(TuneStatus.PRUNED.name)
    assert n_eval >= 1 and n_pruned >= 1
    assert n_eval + n_pruned == 9  # halving never skips a point
    # pruned points were measured at a smaller rung than the survivors
    last_rung = max(p["rung"] for p in res.points)
    for p in res.points:
        if p["status"] == TuneStatus.EVALUATED.name:
            assert p["rung"] == last_rung
            assert p["n_subset"] == min(
                len(f.train_idx)
                for f in stratified_kfold(Y, 2, seed=1))
    # the winner is a final-rung point with the best accuracy there
    finals = [p for p in res.points
              if p["status"] == TuneStatus.EVALUATED.name]
    assert res.winner["cv_accuracy"] == max(
        p["cv_accuracy"] for p in finals)


def test_tune_plateau_early_stop(rings_data):
    X, Y = rings_data
    grid = make_grid([0.5, 1.0, 2.0, 4.0, 8.0], [2.0])
    res = tune(X, Y, grid, _cfg(patience=2))
    statuses = [p["status"] for p in res.points]
    # rings saturates at the first points -> patience fires, tail skipped
    assert TuneStatus.SKIPPED.name in statuses
    skipped = [p for p in res.points
               if p["status"] == TuneStatus.SKIPPED.name]
    for p in skipped:
        assert p["cv_accuracy"] is None and p["n_updates"] == 0
    # skipped points can never be the winner
    assert res.winner["cv_accuracy"] is not None


def test_tune_config_validation():
    with pytest.raises(ValueError, match="schedule"):
        TuneConfig(schedule="random")
    with pytest.raises(ValueError, match="folds"):
        TuneConfig(folds=1)
    with pytest.raises(ValueError, match="eta"):
        TuneConfig(eta=1)
    with pytest.raises(ValueError, match="patience"):
        TuneConfig(patience=0)


# ---------------------------------------------------------- kernel families
def test_tune_over_kernel_families():
    """Each family runs the full schedule with its own warm store; rows
    and the winner carry kernel identity; the winner is the global CV
    argmax (rbf must beat linear on rings — structurally non-linear)."""
    X, Y = rings(n=200, seed=4)
    res = tune(X, Y, make_grid([1.0, 10.0], [2.0]), _cfg(),
               kernels=["rbf", "linear"])
    assert [k["kernel"] for k in res.kernels] == ["rbf", "linear"]
    assert len(res.points) == 4  # 2 points x 2 families
    fams = [r["kernel"] for r in res.points]
    assert fams == ["rbf", "rbf", "linear", "linear"]
    assert res.winner["kernel"] == "rbf"
    rbf_best = max(r["cv_accuracy"] for r in res.points
                   if r["kernel"] == "rbf")
    lin_best = max(r["cv_accuracy"] for r in res.points
                   if r["kernel"] == "linear")
    assert rbf_best > lin_best  # rings are not linearly separable
    # per-family warm chaining: the SECOND point of each family seeds
    for fam in ("rbf", "linear"):
        rows = [r for r in res.points if r["kernel"] == fam]
        assert rows[0]["warm_seeded"] == 0
        assert rows[1]["warm_seeded"] == res.folds


def test_normalize_kernel_specs():
    from tpusvm.tune import normalize_kernel_specs

    base = SVMConfig(degree=2, coef0=1.0)
    specs = normalize_kernel_specs(["linear", {"kernel": "poly"}], base)
    assert specs == [
        {"kernel": "linear", "degree": 2, "coef0": 1.0},
        {"kernel": "poly", "degree": 2, "coef0": 1.0},
    ]
    assert normalize_kernel_specs(None, base) == [
        {"kernel": "rbf", "degree": 2, "coef0": 1.0}]
    with pytest.raises(ValueError, match="duplicate kernel spec"):
        normalize_kernel_specs(["rbf", "rbf"], base)
    with pytest.raises(ValueError, match="unknown kernel family"):
        normalize_kernel_specs(["laplacian"], base)
    # approx families are rejected by NAME (gamma is baked into the
    # feature map; tune's shared-fold-cache sweep cannot apply)
    with pytest.raises(ValueError, match="approximate kernel"):
        normalize_kernel_specs(["rff"], base)


# ----------------------------------------------------------------- results
def test_tune_result_roundtrip_and_table(tmp_path, rings_data):
    X, Y = rings_data
    res = tune(X, Y, make_grid([1.0], [2.0]), _cfg())
    path = str(tmp_path / "r.json")
    save_tune_result(path, res)
    assert is_tune_result(path)
    back = load_tune_result(path)
    assert back.winner == res.winner
    assert back.points == res.points
    assert back.schedule == "grid" and back.warm_start is True
    assert back.kernels == [{"kernel": "rbf", "degree": 3, "coef0": 0.0}]
    table = format_table(back)
    assert "winner: kernel=rbf C=1" in table and "EVALUATED" in table


def test_tune_result_version_gate(tmp_path):
    raw = {"kind": "tpusvm-tune-result", "format_version": 99}
    p = str(tmp_path / "future.json")
    json.dump(raw, open(p, "w"))
    with pytest.raises(ValueError, match="unsupported tune-results format"):
        load_tune_result(p)
    p2 = str(tmp_path / "foreign.json")
    json.dump({"something": "else"}, open(p2, "w"))
    assert not is_tune_result(p2)
    with pytest.raises(ValueError, match="not a tpusvm tune-results"):
        load_tune_result(p2)
    # versioned and right kind but missing fields: loud, named error
    p3 = str(tmp_path / "torn.json")
    json.dump({"kind": "tpusvm-tune-result", "format_version": 2,
               "winner": {}}, open(p3, "w"))
    with pytest.raises(ValueError, match="missing tune-result fields"):
        load_tune_result(p3)
    # v1 files (pre-kernel-axis) hit the version gate, not a field error
    p4 = str(tmp_path / "v1.json")
    json.dump({"kind": "tpusvm-tune-result", "format_version": 1,
               "winner": {}}, open(p4, "w"))
    with pytest.raises(ValueError, match="unsupported tune-results format"):
        load_tune_result(p4)


# --------------------------------------------------------------------- cli
def test_cli_tune_smoke_and_info(tmp_path, capsys):
    from tpusvm.cli import main

    results = str(tmp_path / "tune.json")
    model = str(tmp_path / "winner.npz")
    rc = main(["tune", "--smoke", "--results", results, "--save", model])
    assert rc == 0
    out = capsys.readouterr().out
    assert "tune smoke ok" in out
    assert "winner:" in out

    # info recognises the tune artifact and pretty-prints the table
    rc = main(["info", results])
    assert rc == 0
    out = capsys.readouterr().out
    assert "winner:" in out and "EVALUATED" in out and "grid=2x2" in out

    # ... and still describes model files
    rc = main(["info", model])
    assert rc == 0
    out = capsys.readouterr().out
    assert "model: binary" in out and "SV count:" in out

    # ... and still prints backend info with no path
    rc = main(["info"])
    assert rc == 0
    assert "backend:" in capsys.readouterr().out


def test_cli_tune_rejects_half_grid():
    from tpusvm.cli import main

    with pytest.raises(SystemExit, match="both --C-grid and --gamma-grid"):
        main(["tune", "--synthetic", "rings", "--n", "64",
              "--C-grid", "1,2"])


def test_cli_info_rejects_unknown_artifact(tmp_path):
    from tpusvm.cli import main

    bogus = str(tmp_path / "bogus.bin")
    open(bogus, "wb").write(b"not an artifact")
    with pytest.raises(SystemExit, match="neither a tune-results"):
        main(["info", bogus])
