"""Multi-tenant platform tier (tpusvm/tenants/): per-tenant views over
one shared corpus, the crash-safe tenant store, coalesced fleet
refreshes with solo-parity gates, the supervisor's stage machine, and
the platform-scale serving satellites (2k-entry registry, scandir
watcher)."""

import dataclasses
import json
import os
import time
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm import faults
from tpusvm.autopilot import DriftThresholds
from tpusvm.models import BinarySVC
from tpusvm.serve.refresh import refresh_fit
from tpusvm.status import TenantsStatus
from tpusvm.stream import ShardWriter, ingest_arrays
from tpusvm.tenants import (
    TenantRecord,
    TenantsConfig,
    TenantsState,
    TenantsSupervisor,
    is_tenant_store,
    load_fleet_checkpoint,
    load_store,
    provision_tenants,
    refresh_drifted,
    save_fleet_checkpoint,
    save_store,
    tenant_labels,
    view_fingerprint,
)

# one shared multiclass corpus: K labelled blobs, f64 host rows (the
# serve tier's bitwise served-vs-offline contract is stated for f64
# queries — tests below compare artifacts, but the data idiom matches)
K, D = 4, 4
N0, GROW = 160, 96
_rng = np.random.default_rng(1807)
LABELS = _rng.integers(0, K, size=N0 + GROW).astype(np.int32)
_MEANS = _rng.normal(0.0, 2.5, size=(K, D))
XALL = _MEANS[LABELS] + _rng.normal(0.0, 1.0, size=(N0 + GROW, D))
XALL[N0:] += 0.6  # appended rows are shifted: refreshed != donor

C_PAL, G_PAL = (1.0, 3.0, 10.0), (0.5, 1.5, 5.0)
SOLVER_OPTS = {"q": 16, "max_inner": 8}


def _mk_records(n=5):
    """4 full-view tenants + 1 row-subset tenant — one coalescing
    bucket (the subset view is a per-problem valid mask, not a
    static-key split)."""
    recs = []
    for i in range(n):
        recs.append(TenantRecord(
            tenant_id=f"t{i}", positive_label=i % K,
            C=C_PAL[i % 3], gamma=G_PAL[i % 3],
            row_mod=2 if i == 4 else None,
            row_ofs=1 if i == 4 else 0))
    return recs


def _mk_odd():
    """The different-static-template tenant (provisioned with its own
    SVMConfig) that can never join the shared bucket."""
    return TenantRecord(tenant_id="t5", positive_label=1, C=3.0,
                        gamma=1.5)


# ---------------------------------------------------------------- views

def test_tenant_labels_column_view():
    rec = TenantRecord(tenant_id="a", positive_label=2, C=1.0, gamma=1.0)
    Y, valid = tenant_labels(LABELS, rec)
    assert valid is None
    np.testing.assert_array_equal(
        Y, np.where(LABELS == 2, 1, -1).astype(np.int32))


def test_tenant_labels_row_subset_mask():
    rec = TenantRecord(tenant_id="a", positive_label=1, C=1.0, gamma=1.0,
                       row_mod=3, row_ofs=2)
    Y, valid = tenant_labels(LABELS, rec)
    np.testing.assert_array_equal(
        valid, (np.arange(LABELS.shape[0]) % 3) == 2)
    # live rows keep the +/-1 column view; masked rows are never y=0
    assert set(np.unique(Y)) == {-1, 1}


def test_tenant_labels_degenerate_view_raises():
    labels = np.zeros(16, np.int32)  # all one class
    rec = TenantRecord(tenant_id="a", positive_label=0, C=1.0, gamma=1.0)
    with pytest.raises(ValueError, match="degenerate"):
        tenant_labels(labels, rec)
    # a subset view can be degenerate even when the full view is not
    labels = np.array([0, 1] * 8, np.int32)
    rec = TenantRecord(tenant_id="b", positive_label=0, C=1.0, gamma=1.0,
                       row_mod=2, row_ofs=0)
    with pytest.raises(ValueError, match="degenerate"):
        tenant_labels(labels, rec)


def test_view_fingerprint_tracks_view_not_rows():
    rec = TenantRecord(tenant_id="a", positive_label=1, C=1.0, gamma=1.0,
                       row_mod=2, row_ofs=0)
    fp1 = view_fingerprint(*tenant_labels(LABELS, rec))
    fp2 = view_fingerprint(*tenant_labels(LABELS, rec))
    assert fp1 == fp2
    grown = np.concatenate([LABELS, [1]]).astype(np.int32)
    assert view_fingerprint(*tenant_labels(grown, rec)) != fp1


def test_record_validation_rejects_bad_hyperparams():
    with pytest.raises(ValueError, match="C must be"):
        TenantRecord(tenant_id="a", positive_label=0, C=0.0,
                     gamma=1.0).validate()
    with pytest.raises(ValueError, match="gamma must be"):
        TenantRecord(tenant_id="a", positive_label=0, C=1.0,
                     gamma=float("nan")).validate()
    with pytest.raises(ValueError, match="row_ofs"):
        TenantRecord(tenant_id="a", positive_label=0, C=1.0, gamma=1.0,
                     row_mod=2, row_ofs=2).validate()


# ---------------------------------------------------------------- store

def _state():
    st = TenantsState(seed=7, tick=3, stage="fitting",
                      inflight={"tenant_ids": ["a"], "stage_rows": 12},
                      generation=2, refreshes=5)
    st.tenants["a"] = TenantRecord(
        tenant_id="a", positive_label=0, C=1.0, gamma=2.0,
        model_path="/x/a.npz", generation=2, rows_at_refresh=12)
    st.tenants["b"] = TenantRecord(
        tenant_id="b", positive_label=1, C=3.0, gamma=0.5,
        row_mod=2, row_ofs=1)
    return st


def test_store_roundtrip(tmp_path):
    p = str(tmp_path / "store.json")
    st = _state()
    save_store(p, st)
    assert is_tenant_store(p)
    assert load_store(p).to_json() == st.to_json()


def test_store_rejects_corruption(tmp_path):
    p = str(tmp_path / "store.json")
    save_store(p, _state())
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) // 2] ^= 0x20
    open(p, "wb").write(bytes(raw))
    with pytest.raises(ValueError):
        load_store(p)


def test_store_rejects_future_version_and_unknown_fields(tmp_path):
    from tpusvm.tenants.store import _canonical

    p = str(tmp_path / "store.json")
    save_store(p, _state())
    doc = json.load(open(p))
    doc["store_version"] = 99
    json.dump(doc, open(p, "w"))
    with pytest.raises(ValueError, match="version"):
        load_store(p)
    # an unknown field must be refused even when the CRC is VALID (a
    # newer tpusvm wrote it) — re-sign the tampered payload like a
    # newer writer would
    save_store(p, _state())
    doc = json.load(open(p))
    doc.pop("crc32")
    doc["from_the_future"] = 1
    doc["crc32"] = zlib.crc32(_canonical(doc)) & 0xFFFFFFFF
    json.dump(doc, open(p, "w"))
    with pytest.raises(ValueError, match="unknown fields"):
        load_store(p)


def test_fleet_checkpoint_fingerprint_refusal(tmp_path):
    from tpusvm.solver.blocked import _OuterState

    p = str(tmp_path / "fleet.ck.npz")
    rng = np.random.default_rng(0)
    st = _OuterState(*(np.asarray(rng.normal(size=(2, 8)), np.float32)
                       for _ in _OuterState._fields))
    save_fleet_checkpoint(p, st, {"launch": "aaa", "rows": 64})
    back = load_fleet_checkpoint(p, {"launch": "aaa", "rows": 64})
    for a, b in zip(st, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="rows"):
        load_fleet_checkpoint(p, {"launch": "aaa", "rows": 128})


# ------------------------------------------- coalesced refresh parity

@pytest.fixture(scope="module")
def platform(tmp_path_factory):
    """Donors provisioned on the N0-row prefix, then the three refresh
    arms over the grown corpus: per-tenant solo controls, one warm
    coalesced launch, one cold coalesced launch, and a warm launch with
    the records in reversed order (lane-placement invariance)."""
    from tpusvm.config import SVMConfig

    td = tmp_path_factory.mktemp("tenants_platform")
    donors = str(td / "donors")
    os.makedirs(donors)
    recs = _mk_records()
    provision_tenants(XALL[:N0], LABELS[:N0], recs, artifacts_dir=donors,
                      solver_opts=SOLVER_OPTS)
    # one tenant whose donor carries a DIFFERENT static template: its
    # launch key can never join the shared bucket, so refresh_drifted
    # must route it through the solo refresh_fit fallback
    odd = _mk_odd()
    provision_tenants(XALL[:N0], LABELS[:N0], [odd],
                      artifacts_dir=donors,
                      config=SVMConfig(eps=1e-11),
                      solver_opts=SOLVER_OPTS)
    recs = recs + [odd]

    solo = str(td / "solo")
    os.makedirs(solo)
    solo_models = {}
    for rec in recs:
        # the platform convention: a row-subset tenant solves over the
        # FULL corpus with a valid mask (coalesce.py's solo fallback
        # does the same), so SV ids live in shared-corpus row space
        Y, valid = tenant_labels(LABELS, rec)
        opts = dict(SOLVER_OPTS)
        if valid is not None:
            opts["valid"] = valid
        solo_models[rec.tenant_id] = refresh_fit(
            rec.model_path, XALL, Y,
            out_path=os.path.join(solo, rec.tenant_id + ".npz"),
            solver_opts=opts)

    arms = {}
    for arm, warm, order in (("warm", True, 1), ("cold", False, 1),
                             ("warm_rev", True, -1)):
        adir = str(td / arm)
        os.makedirs(adir)
        arecs = (_mk_records() + [_mk_odd()])[::order]
        for r in arecs:
            r.model_path = os.path.join(donors, r.tenant_id + ".npz")
        arms[arm] = (refresh_drifted(
            XALL, LABELS, arecs, artifacts_dir=adir, warm=warm,
            solver_opts=SOLVER_OPTS), adir)
    return recs, solo, solo_models, arms


def test_coalesced_matches_solo_exactly(platform):
    """The tier's load-bearing parity: each coalesced tenant keeps its
    solo control's exact SV-ID set, status and held-out accuracy; b and
    alpha land within the cross-engine band (batched vs single-head XLA
    programs round differently — same physics as tests/test_fleet.py
    and the ovr band in tests/test_models.py; bitwise is a same-program
    property, exercised by the lane-invariance test below)."""
    recs, solo, solo_models, arms = platform
    outcomes, adir = arms["warm"]
    modes = {r.tenant_id: outcomes[r.tenant_id]["mode"] for r in recs}
    # the row-subset view is a per-problem axis (valid mask), NOT a
    # static-key split — t4 coalesces with the full-view bucket; only
    # the different-template tenant t5 falls back solo
    assert [modes[f"t{i}"] for i in range(6)] == \
        ["fleet", "fleet", "fleet", "fleet", "fleet", "solo"]
    for rec in recs:
        m = BinarySVC.load(os.path.join(adir, rec.tenant_id + ".npz"))
        ctl = solo_models[rec.tenant_id]
        assert m.status_ == ctl.status_, rec.tenant_id
        np.testing.assert_array_equal(m.sv_ids_, ctl.sv_ids_)
        np.testing.assert_allclose(m.b_, ctl.b_, atol=1e-4)
        np.testing.assert_allclose(m.sv_alpha_, ctl.sv_alpha_,
                                   atol=1e-3)
        Y, _ = tenant_labels(LABELS, rec)
        pred_m = np.asarray(m.decision_function(XALL)) >= 0
        pred_c = np.asarray(ctl.decision_function(XALL)) >= 0
        assert (pred_m == (Y == 1)).mean() == \
            (pred_c == (Y == 1)).mean(), rec.tenant_id


def test_record_order_is_bitwise_invariant(platform):
    """Reversing the record order handed to refresh_drifted must not
    change a single artifact byte: coalesce_drifted sorts tenant ids
    inside each launch group, so lane assignment — and therefore every
    lane-sliced solve — is deterministic in the SET of drifted tenants,
    not the order the caller enumerated them in."""
    recs, _, _, arms = platform
    _, fwd = arms["warm"]
    _, rev = arms["warm_rev"]
    for rec in recs:
        a = np.load(os.path.join(fwd, rec.tenant_id + ".npz"))
        b = np.load(os.path.join(rev, rec.tenant_id + ".npz"))
        assert set(a.files) == set(b.files)
        for k in a.files:
            assert a[k].tobytes() == b[k].tobytes(), \
                f"{rec.tenant_id}:{k}"


def test_warm_fleet_beats_cold(platform):
    """The deployed_seed alpha0 lanes must do real work: the warm
    coalesced launch spends strictly fewer total SMO updates than the
    cold control."""
    _, _, _, arms = platform
    warm_updates = sum(int(o["n_iter"])
                       for o in arms["warm"][0].values())
    cold_updates = sum(int(o["n_iter"])
                       for o in arms["cold"][0].values())
    assert warm_updates < cold_updates


def test_checkpointed_refresh_kill_resume_bit_identity(tmp_path,
                                                       platform):
    """SIGKILL mid-fleet-solve at a segment-checkpoint write, then
    resume: the recovered launch must continue from the durable carry
    to artifacts BIT-identical to an uninterrupted control — the
    supervisor's crash-window contract at unit scale (the 64-tenant
    version lives in `python -m tpusvm.faults tenant-chaos-smoke`)."""
    recs, _, _, _ = platform
    donors = {r.tenant_id: r.model_path for r in recs}

    def run(outdir, plan):
        arecs = _mk_records() + [_mk_odd()]
        for r in arecs:
            r.model_path = donors[r.tenant_id]
        ckdir = str(tmp_path / (os.path.basename(outdir) + "_ck"))
        os.makedirs(ckdir, exist_ok=True)
        kwargs = dict(artifacts_dir=outdir, checkpoint_dir=ckdir,
                      checkpoint_every=2, resume=True,
                      solver_opts=SOLVER_OPTS)
        if plan is None:
            return refresh_drifted(XALL, LABELS, arecs, **kwargs)
        with faults.active(plan):
            with pytest.raises(faults.SimulatedKill):
                refresh_drifted(XALL, LABELS, arecs, **kwargs)
        faults.deactivate()
        assert any(f.endswith(".ck.npz") for f in os.listdir(ckdir)), \
            "no durable checkpoint at the kill point"
        return refresh_drifted(XALL, LABELS, arecs, **kwargs)

    ctl_dir = str(tmp_path / "ctl")
    os.makedirs(ctl_dir)
    run(ctl_dir, None)
    kill_dir = str(tmp_path / "kill")
    os.makedirs(kill_dir)
    plan = faults.FaultPlan([faults.FaultRule(
        point="tenants.store", kind="kill", at_hit=2)], seed=5)
    run(kill_dir, plan)
    for rec in recs:
        a = np.load(os.path.join(ctl_dir, rec.tenant_id + ".npz"))
        b = np.load(os.path.join(kill_dir, rec.tenant_id + ".npz"))
        for k in a.files:
            assert a[k].tobytes() == b[k].tobytes(), \
                f"{rec.tenant_id}:{k}"


# ------------------------------------------------------- supervisor

def _mk_platform_dir(tmp_path, n_tenants=3):
    data = str(tmp_path / "data")
    ingest_arrays(data, XALL[:N0], LABELS[:N0], rows_per_shard=64)
    donors = str(tmp_path / "donors")
    os.makedirs(donors)
    recs = _mk_records(n_tenants)
    provision_tenants(XALL[:N0], LABELS[:N0], recs, artifacts_dir=donors,
                      solver_opts=SOLVER_OPTS)
    return data, recs


def _cfg(tmp_path, data, **kw):
    base = dict(
        data_dir=data,
        store_path=str(tmp_path / "store.json"),
        artifacts_dir=str(tmp_path / "artifacts"),
        thresholds=DriftThresholds(growth=0.25, feature=None,
                                   score=None, jitter_frac=0.0),
        hysteresis=1, cooldown_s=0.0, checkpoint_every=4, min_fleet=2,
        seed=11, solver_opts=SOLVER_OPTS,
    )
    base.update(kw)
    return TenantsConfig(**base)


def _grow(data):
    w = ShardWriter.open_append(data)
    w.append(XALL[N0:], LABELS[N0:])
    w.close()


def test_supervisor_watch_refresh_cycle(tmp_path):
    data, recs = _mk_platform_dir(tmp_path)
    sup = TenantsSupervisor(_cfg(tmp_path, data), log_fn=None)
    for rec in recs:
        sup.register(rec)
    out = sup.tick()
    assert out["status"] == TenantsStatus.WATCHING
    _grow(data)
    out = sup.tick()
    assert out["status"] == TenantsStatus.REFRESHED
    assert sorted(out["drifted"]) == [r.tenant_id for r in recs]
    for rec in recs:
        st = sup.state.tenants[rec.tenant_id]
        assert st.generation == 1
        assert st.rows_at_refresh == N0 + GROW
        assert os.path.exists(st.model_path)
    # refreshed == watching again until more rows arrive
    assert sup.tick()["status"] == TenantsStatus.WATCHING
    # ...and the whole decision memory is durable: a resumed supervisor
    # sees the identical registry + counters
    sup2 = TenantsSupervisor(_cfg(tmp_path, data), resume=True,
                             log_fn=None)
    assert sup2.state.to_json() == sup.state.to_json()


def test_supervisor_hysteresis_arms_before_firing(tmp_path):
    data, recs = _mk_platform_dir(tmp_path, n_tenants=2)
    sup = TenantsSupervisor(_cfg(tmp_path, data, hysteresis=2),
                            log_fn=None)
    for rec in recs:
        sup.register(rec)
    _grow(data)
    assert sup.tick()["status"] == TenantsStatus.TRIGGERED_HYSTERESIS
    assert sup.tick()["status"] == TenantsStatus.REFRESHED


def test_supervisor_breaker_suppresses_after_failures(tmp_path,
                                                      monkeypatch):
    data, recs = _mk_platform_dir(tmp_path, n_tenants=2)
    cfg = _cfg(tmp_path, data, breaker_threshold=1,
               breaker_cooldown_s=3600.0)
    sup = TenantsSupervisor(cfg, log_fn=None)
    for rec in recs:
        sup.register(rec)

    # a refresh stage that dies (infra outage, not a per-tenant error)
    # must come back as a COUNTED status — previous generations keep
    # serving — and feed the breaker, which then suppresses the retry
    def boom(*a, **kw):
        raise RuntimeError("refresh infra down")

    monkeypatch.setattr("tpusvm.tenants.loop.refresh_drifted", boom)
    _grow(data)
    assert sup.tick()["status"] == TenantsStatus.REFRESH_FAILED
    assert sup.state.failures >= 1
    assert sup.tick()["status"] == TenantsStatus.SUPPRESSED_BREAKER


def test_supervisor_resume_refuses_seed_mismatch(tmp_path):
    data, recs = _mk_platform_dir(tmp_path, n_tenants=2)
    sup = TenantsSupervisor(_cfg(tmp_path, data, seed=11), log_fn=None)
    for rec in recs:
        sup.register(rec)
    with pytest.raises(ValueError, match="seed"):
        TenantsSupervisor(_cfg(tmp_path, data, seed=12), resume=True,
                          log_fn=None)


def test_register_rejects_duplicates(tmp_path):
    data, recs = _mk_platform_dir(tmp_path, n_tenants=2)
    sup = TenantsSupervisor(_cfg(tmp_path, data), log_fn=None)
    sup.register(recs[0])
    with pytest.raises(ValueError, match="already registered"):
        sup.register(dataclasses.replace(recs[0]))


# ------------------------------------------- platform-scale serving

def _tiny_artifact(tmp_path):
    from tpusvm.config import SVMConfig
    from tpusvm.data import rings

    Xr, Yr = rings(n=80, seed=3)
    p = str(tmp_path / "tiny.npz")
    BinarySVC(SVMConfig(C=10.0, gamma=10.0),
              dtype=jnp.float32).fit(Xr, Yr).save(p)
    return p


def test_registry_ops_stay_flat_at_2k_entries(tmp_path):
    """The tenant platform hangs thousands of entries off ONE registry;
    swap and get_versioned are dict-op + lock, so per-op latency must
    not scale with the registry size (a linear scan sneaking in would
    turn every request into an O(tenants) stall)."""
    from tpusvm.serve.registry import ModelEntry, ModelRegistry

    entry = ModelEntry.from_path("m0", _tiny_artifact(tmp_path))

    def bench(n_entries, ops=3000):
        reg = ModelRegistry()
        for i in range(n_entries):
            reg.add(dataclasses.replace(entry, name=f"m{i}",
                                        generation=1))
        probe = f"m{n_entries - 1}"
        t0 = time.perf_counter()
        for _ in range(ops):
            reg.get_versioned(probe)
        t_get = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(ops):
            reg.swap(dataclasses.replace(entry, name=probe))
        t_swap = time.perf_counter() - t0
        return t_get / ops, t_swap / ops

    small_get, small_swap = bench(16)
    big_get, big_swap = bench(2048)
    # 128x the entries must not cost anywhere near 128x per op; the
    # bound is deliberately loose (CI noise) — it catches O(n), not jitter
    assert big_get < small_get * 25 + 1e-4, (small_get, big_get)
    assert big_swap < small_swap * 25 + 1e-4, (small_swap, big_swap)


def test_watcher_scan_is_one_sweep(tmp_path):
    """_scan: name-filtered scandir index — .npz entries only, junk and
    subdirectories skipped, missing directory = empty (not a crash),
    deterministic sorted order."""
    from tpusvm.serve.watch import ModelWatcher

    d = str(tmp_path / "watch")
    os.makedirs(d)
    for name in ("b.npz", "a.npz", "notes.txt", "c.npz.tmp"):
        open(os.path.join(d, name), "wb").write(b"x")
    os.makedirs(os.path.join(d, "sub.npz"))  # a DIRECTORY named *.npz
    w = ModelWatcher(server=None, watch_dir=d, log_fn=None)
    got = w._scan()
    assert [os.path.basename(p) for p, _ in got] == ["a.npz", "b.npz"]
    assert all(isinstance(m, float) for _, m in got)
    w_missing = ModelWatcher(server=None,
                             watch_dir=str(tmp_path / "nope"),
                             log_fn=None)
    assert w_missing._scan() == []
