"""tpusvm.pod tests: wire protocol, durable round state, leaf loading,
and the pod-vs-in-process parity gates.

The pod tier's whole claim is "same cascade, different transport": a
coordinator plus worker PROCESSES connected by framed socket messages
must walk the identical SV-ID fixed point as the in-process cascade on
the same rows — bit-identical alpha bytes and b, not tolerances — while
each worker streams only its own manifest shards. The parity tests here
run on plain CPU jax with zero shard_map skips (cascade_fit's host
fallback is the in-process control arm).
"""

import os
import socket

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import CascadeConfig, SVMConfig
from tpusvm.data import MinMaxScaler, rings
from tpusvm.oracle import get_sv_indices, smo_train
from tpusvm.parallel.cascade import _leaf_buf, cascade_fit
from tpusvm.parallel.svbuffer import SVBuffer
from tpusvm.pod import pod_fit
from tpusvm.pod.protocol import recv_msg, send_msg
from tpusvm.pod.state import (
    check_pod_round_state_config,
    load_pod_round_state,
    save_pod_round_state,
)
from tpusvm.stream import (
    ShardReader,
    ingest_arrays,
    open_dataset,
    partition_from_dataset,
)

CFG = SVMConfig(C=10.0, gamma=10.0, max_rounds=12)
P = 4


@pytest.fixture(scope="module")
def rings_data():
    return rings(n=192, seed=3)


@pytest.fixture(scope="module")
def dataset(tmp_path_factory, rings_data):
    X, Y = rings_data
    out = str(tmp_path_factory.mktemp("pod") / "ds")
    ingest_arrays(out, X, Y, rows_per_shard=24)
    return open_dataset(out)


@pytest.fixture(scope="module")
def oracle(rings_data):
    X, Y = rings_data
    Xs = MinMaxScaler().fit_transform(X)
    return Xs, Y, smo_train(Xs, Y, CFG)


# ---------------------------------------------------------------- protocol
def test_protocol_roundtrip_bit_exact():
    a, b = socket.socketpair()
    try:
        arrays = {
            "f64": np.linspace(-1, 1, 7, dtype=np.float64),
            "f32": np.float32([[1.5, -2.25], [0.0, 3e-8]]),
            "i32": np.arange(-3, 3, dtype=np.int32),
            "mask": np.array([True, False, True]),
        }
        send_msg(a, {"op": "train", "req": 7, "b": 0.5}, arrays)
        meta, got = recv_msg(b)
        assert meta == {"op": "train", "req": 7, "b": 0.5}
        assert sorted(got) == sorted(arrays)
        for k, v in arrays.items():
            assert got[k].dtype == v.dtype
            assert got[k].tobytes() == v.tobytes()

        # array-less message: empty npz section, meta only
        send_msg(b, {"op": "bye"})
        meta, got = recv_msg(a)
        assert meta == {"op": "bye"} and got == {}
    finally:
        a.close()
        b.close()


def test_protocol_short_frame_is_peer_death():
    # a worker SIGKILLed mid-write leaves a short frame: the reader must
    # surface ConnectionError (peer death), never a truncated message
    a, b = socket.socketpair()
    try:
        import struct

        a.sendall(struct.pack(">I", 64) + b"\x00\x00\x00\x04abcd")
        a.close()
        with pytest.raises(ConnectionError, match="mid-frame"):
            recv_msg(b)
    finally:
        b.close()


def test_protocol_bad_lengths_rejected():
    import struct

    for frame in (struct.pack(">I", 2) + b"xx",          # total < 4
                  struct.pack(">II", 8, 100) + b"xxxx"):  # meta > frame
        a, b = socket.socketpair()
        try:
            a.sendall(frame)
            a.close()
            with pytest.raises(ConnectionError):
                recv_msg(b)
        finally:
            b.close()


# ------------------------------------------------------------- round state
def _rand_buf(rng, cap=16, dim=4):
    return SVBuffer(
        X=jnp.asarray(rng.normal(size=(cap, dim)), jnp.float32),
        Y=jnp.asarray(np.where(rng.random(cap) < 0.5, 1, -1)),
        alpha=jnp.asarray(rng.random(cap), jnp.float64),
        ids=jnp.arange(cap, dtype=jnp.int32),
        valid=jnp.asarray(rng.random(cap) < 0.75),
    )


def test_pod_round_state_roundtrip(tmp_path):
    rng = np.random.default_rng(7)
    path = str(tmp_path / "round.npz")
    first = _rand_buf(rng)
    save_pod_round_state(path, first, prev_ids={3, 1}, rnd=1, b=0.25,
                         n_leaves=P, topology="tree")
    buf = _rand_buf(rng)
    save_pod_round_state(path, buf, prev_ids={5, 2, 9}, rnd=2, b=-1.5,
                         n_leaves=P, topology="tree")
    sv, prev_ids, next_round, b = load_pod_round_state(path)
    for f in SVBuffer._fields:
        assert np.asarray(getattr(sv, f)).tobytes() == \
            np.asarray(getattr(buf, f)).tobytes(), f
    # alpha keeps its STORED dtype: truncating mixed-precision duals
    # would fork the resumed trajectory from an uninterrupted run
    assert np.asarray(sv.alpha).dtype == np.float64
    assert prev_ids == {2, 5, 9}
    assert next_round == 3 and b == -1.5
    check_pod_round_state_config(path, P, "tree")


def test_pod_round_state_config_gate(tmp_path):
    path = str(tmp_path / "round.npz")
    save_pod_round_state(path, _rand_buf(np.random.default_rng(0)),
                         prev_ids=set(), rnd=1, b=0.0,
                         n_leaves=P, topology="star")
    with pytest.raises(ValueError, match="n_leaves=4"):
        check_pod_round_state_config(path, 8, "star")
    with pytest.raises(ValueError, match="topology"):
        check_pod_round_state_config(path, P, "tree")


# ------------------------------------------------------------ leaf loading
@pytest.mark.parametrize("stratified", [False, True])
def test_leaf_rows_pin_partition_semantics(dataset, stratified):
    # satellite pin: a worker's load_leaf must fill its padded buffer
    # BYTE-identically to slicing stream.partition_from_dataset — same
    # assignment, same scaler, same float64 staging before the cast —
    # so pod SV IDs live in the global row space every other path uses
    from tpusvm.pod.worker import load_leaf

    part = partition_from_dataset(dataset, P, stratified=stratified,
                                  scaler=dataset.scaler())
    stacked = SVBuffer(
        X=jnp.asarray(part.X, jnp.float32),
        Y=jnp.asarray(part.Y),
        alpha=jnp.zeros(part.Y.shape, jnp.float32),
        ids=jnp.asarray(part.ids),
        valid=jnp.asarray(part.valid),
    )
    for r in range(P):
        want = _leaf_buf(stacked, r)
        got, rows, shards_read, live = load_leaf(
            dataset, r, P, stratified=stratified, prefetch_depth=2,
            scale=True, dtype=jnp.float32)
        for f in SVBuffer._fields:
            assert np.asarray(getattr(got, f)).tobytes() == \
                np.asarray(getattr(want, f)).tobytes(), (r, f)
        assert rows == int(part.count[r])
        # the residency contract: only the leaf's OWN shards are read,
        # never more than prefetch_depth + 1 resident at once
        assert shards_read <= dataset.n_shards
        assert live <= 3


def test_shard_reader_subset(dataset, rings_data):
    X, _ = rings_data
    sub = [1, 3, 4]
    reader = ShardReader(dataset, prefetch_depth=2, shards=sub)
    chunks = list(reader)
    assert len(chunks) == len(sub)
    for (Xc, _), i in zip(chunks, sub):
        info = dataset.manifest.shards[i]
        want = np.ascontiguousarray(
            X[info.row_start:info.row_start + info.n_rows])
        assert Xc.tobytes() == want.tobytes()
    assert reader.max_live_shards <= 3
    with pytest.raises(ValueError, match="unique"):
        ShardReader(dataset, shards=[1, 1])
    with pytest.raises(IndexError):
        ShardReader(dataset, shards=[dataset.n_shards])


# ----------------------------------------------------------------- parity
@pytest.mark.parametrize("topology", ["tree", "star"])
def test_pod_parity_with_inprocess_cascade(dataset, oracle, topology):
    # THE pod acceptance gate: worker processes fed by manifest shards
    # must reproduce the in-process cascade BIT-identically (same SV-ID
    # set, same alpha bytes, same b) and recover the serial oracle's SV
    # set — with every dataset row accounted for across the workers and
    # per-worker shard residency within the prefetch bound
    Xs, Y, o = oracle
    cc = CascadeConfig(n_shards=P, sv_capacity=128, topology=topology)
    ctrl = cascade_fit(Xs, Y, CFG, cc)
    res = pod_fit(str(dataset.path), CFG, cc)

    assert res.converged and ctrl.converged
    assert set(res.sv_ids.tolist()) == set(ctrl.sv_ids.tolist())
    assert np.asarray(res.sv_alpha).tobytes() == \
        np.asarray(ctrl.sv_alpha).tobytes()
    assert res.b == ctrl.b
    assert res.rounds == ctrl.rounds
    assert set(res.sv_ids.tolist()) == set(get_sv_indices(o.alpha).tolist())
    np.testing.assert_allclose(res.b, o.b, atol=1e-4)

    assert res.topology == topology and res.n_leaves == P
    assert sum(res.worker_rows) == len(Y)
    assert res.revives == 0
    assert max(res.worker_max_live_shards) <= 3


def test_pod_shrinking_leaves_recover_oracle(dataset, oracle):
    # the PR 9 ladder the shard_map cascade REJECTS runs on pod leaves:
    # the shrinking driver segments each leaf solve host-side, and the
    # SV-ID fixed point still lands on the oracle's set
    _, Y, o = oracle
    cc = CascadeConfig(n_shards=P, sv_capacity=128)
    res = pod_fit(str(dataset.path), CFG, cc, solver="blocked",
                  solver_opts={"q": 64, "shrink_every": 2})
    assert res.converged
    assert set(res.sv_ids.tolist()) == set(get_sv_indices(o.alpha).tolist())
    np.testing.assert_allclose(res.b, o.b, atol=1e-4)


def test_pod_rejects_shrink_driver_keys_for_pair_solver(dataset):
    with pytest.raises(ValueError, match="shrinking driver"):
        pod_fit(str(dataset.path), CFG, CascadeConfig(n_shards=P),
                solver="pair", solver_opts={"shrink_every": 2})
    with pytest.raises(ValueError, match="unknown solver"):
        pod_fit(str(dataset.path), CFG, CascadeConfig(n_shards=P),
                solver="fleet")


@pytest.mark.slow
def test_pod_coordinator_kill_resume_bit_identical(dataset, tmp_path):
    # the chaos contract in-test (the CI gate is `python -m tpusvm.faults
    # pod-chaos-smoke`): a coordinator killed entering round 2 leaves a
    # durable round-1 checkpoint; a fresh coordinator resumed from it is
    # bit-identical to an uninterrupted control
    from tpusvm import faults

    cc = CascadeConfig(n_shards=P, sv_capacity=128, topology="tree")
    ctrl = pod_fit(str(dataset.path), CFG, cc)
    ck = str(tmp_path / "ck.npz")
    with pytest.raises(faults.SimulatedKill):
        with faults.active(faults.FaultPlan(
                [faults.FaultRule(point="pod.round", kind="kill",
                                  at_hit=2)])):
            pod_fit(str(dataset.path), CFG, cc, checkpoint_path=ck)
    assert os.path.exists(ck)
    res = pod_fit(str(dataset.path), CFG, cc, checkpoint_path=ck,
                  resume=True)
    assert set(res.sv_ids.tolist()) == set(ctrl.sv_ids.tolist())
    assert np.asarray(res.sv_alpha).tobytes() == \
        np.asarray(ctrl.sv_alpha).tobytes()
    assert res.b == ctrl.b


def test_pod_checkpoint_topology_mismatch_refused(dataset, tmp_path):
    ck = str(tmp_path / "ck.npz")
    pod_fit(str(dataset.path), CFG,
            CascadeConfig(n_shards=P, sv_capacity=128, topology="tree"),
            checkpoint_path=ck)
    with pytest.raises(ValueError, match="topology"):
        pod_fit(str(dataset.path), CFG,
                CascadeConfig(n_shards=P, sv_capacity=128,
                              topology="star"),
                checkpoint_path=ck, resume=True)
