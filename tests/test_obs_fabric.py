"""Tests for the distributed observability fabric (ISSUE 20).

Contracts:
  * TraceContext round-trips through its dict form (pod frames) and the
    X-Tpusvm-Trace header form; every malformed input degrades to None,
    never to an exception (a peer speaking another dialect must not
    crash the receiver);
  * pod protocol frames carry the context as a free meta key — old
    frames (no key) parse unchanged and attach_ctx(meta, None) is a
    no-op passthrough;
  * a role-ful Tracer writes its fleet identity into the meta record
    and mints contexts naming the innermost open span; a role-less
    tracer keeps the exact meta shape older builds wrote;
  * obs.report stitches merged trace files into ONE timeline: worker
    root spans re-parent under the coordinator span named by the
    propagated context (file-level meta ctx), per-request spans under
    exactly the originating span (span-level attrs ctx), and
    reparent_stats machine-checks it (0 unresolved);
  * fleet aggregation: merge_fleet tags every series with its origin
    (role, instance) and the merged page equals the sum of the
    per-process pages exactly; FleetCollector derives qps from counter
    deltas on an injected clock; the `tpusvm top` table is a pure
    function of its inputs (golden);
  * the serve HTTP frontend exports /metrics.json as a parseable fleet
    payload and lands traced predicts as serve.request spans carrying
    the propagated ctx; the router injects a fresh context into its
    outbound header (and keeps the 3-arg transport form for injected
    transports that predate trace propagation);
  * benchdiff knows the obs_fabric schema: identity/usability columns
    are exact, the overhead columns are timing rules skipped at smoke.
"""

import json
import socket
import urllib.request

import numpy as np
import pytest

from tpusvm.obs.fleet import (
    FleetCollector,
    FleetView,
    format_top,
    merge_fleet,
    parse_payload,
    read_snapshot_file,
    render_fleet_text,
    snapshot_payload,
    top_rows,
    write_snapshot_file,
)
from tpusvm.obs.registry import MetricsRegistry
from tpusvm.obs.report import (
    cross_process_spans,
    format_round_gantt,
    format_timeline,
    merge_trace_files,
    render_report,
    reparent_stats,
)
from tpusvm.obs.trace import TRACE_HEADER, TraceContext, Tracer, read_trace
from tpusvm.pod.protocol import attach_ctx, extract_ctx, recv_msg, send_msg


class FakeClock:
    """Deterministic monotonic clock for bit-stable trace files."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


# ----------------------------------------------------------- TraceContext
def test_trace_context_dict_roundtrip_and_junk():
    ctx = TraceContext(trace_id="abcd1234", span_id=7, role="router", pid=99)
    assert TraceContext.from_dict(ctx.to_dict()) == ctx
    # span_id None survives (minted outside any span)
    root = TraceContext(trace_id="abcd1234", span_id=None, role="serve",
                        pid=1)
    assert TraceContext.from_dict(root.to_dict()) == root
    # malformed payloads degrade to None, never raise
    for junk in (None, "x", 7, [], {},
                 {"trace_id": "t", "role": "r"},              # missing pid
                 {"trace_id": 5, "role": "r", "pid": 1},       # bad trace_id
                 {"trace_id": "t", "role": None, "pid": 1},    # bad role
                 {"trace_id": "t", "role": "r", "pid": True},  # bool pid
                 {"trace_id": "t", "role": "r", "pid": 1,
                  "span_id": "3"}):                            # str span_id
        assert TraceContext.from_dict(junk) is None


def test_trace_context_header_roundtrip_and_junk():
    ctx = TraceContext(trace_id="abcd1234", span_id=7, role="router", pid=99)
    assert ctx.to_header() == "1;abcd1234;7;router;99"
    assert TraceContext.from_header(ctx.to_header()) == ctx
    # span_id None serializes as "-"
    root = TraceContext(trace_id="t0", span_id=None, role="serve", pid=2)
    assert root.to_header() == "1;t0;-;serve;2"
    assert TraceContext.from_header(root.to_header()) == root
    for junk in (None, "", "garbage", "2;t;3;r;1",   # unknown version
                 "1;t;3;r",                           # 4 parts
                 "1;;3;r;1",                          # empty trace_id
                 "1;t;3;;1",                          # empty role
                 "1;t;x;r;1",                         # bad span_id
                 "1;t;3;r;nope"):                     # bad pid
        assert TraceContext.from_header(junk) is None


# ----------------------------------------------------- protocol ctx frames
def test_protocol_frames_carry_ctx_and_stay_back_compatible():
    ctx = TraceContext(trace_id="feed01", span_id=3, role="pod-coordinator",
                       pid=17)
    a, b = socket.socketpair()
    try:
        meta = {"op": "train", "round": 2}
        send_msg(a, attach_ctx(meta, ctx), arrays={"x": np.arange(3)})
        got, arrays = recv_msg(b)
        assert extract_ctx(got) == ctx
        assert got["op"] == "train" and got["round"] == 2
        assert np.array_equal(arrays["x"], np.arange(3))
        # the sender's meta dict was not mutated (attach_ctx copies)
        assert "ctx" not in meta
        # an old frame (no ctx key) parses unchanged; extract degrades
        send_msg(a, meta)
        old, _ = recv_msg(b)
        assert old == meta and extract_ctx(old) is None
        # junk under the key degrades to None too
        send_msg(a, {"op": "x", "ctx": "not-a-dict"})
        junk, _ = recv_msg(b)
        assert extract_ctx(junk) is None
    finally:
        a.close()
        b.close()
    # None passthrough keeps call sites branch-free
    m = {"op": "shutdown"}
    assert attach_ctx(m, None) is m


# -------------------------------------------------------- Tracer identity
def test_tracer_role_identity_and_ctx_minting(tmp_path):
    path = str(tmp_path / "coord.jsonl")
    with Tracer(path, clock=FakeClock(), wall=lambda: 0.0,
                role="pod-coordinator", trace_id="tid0") as tr:
        assert tr.ctx() == TraceContext("tid0", None, "pod-coordinator",
                                        tr.pid)
        with tr.span("pod.fit"):
            inner = tr.ctx()
            assert inner.span_id == 1  # the innermost open span's id
    meta = read_trace(path)[0]
    assert meta["trace_id"] == "tid0"
    assert meta["role"] == "pod-coordinator" and meta["pid"] == tr.pid
    assert "ctx" not in meta  # not spawned with one

    # a role-ful tracer without an explicit trace_id mints one
    auto = Tracer(str(tmp_path / "auto.jsonl"), role="serve")
    assert isinstance(auto.trace_id, str) and len(auto.trace_id) == 16
    auto.close()

    # roles must survive the ';'-separated header wire format
    with pytest.raises(ValueError, match=";"):
        Tracer(str(tmp_path / "bad.jsonl"), role="a;b")


def test_anonymous_tracer_meta_is_identity_free(tmp_path):
    path = str(tmp_path / "anon.jsonl")
    with Tracer(path, clock=FakeClock(), wall=lambda: 0.0) as tr:
        with pytest.raises(ValueError, match="role"):
            tr.ctx()
    meta = read_trace(path)[0]
    # byte-compat contract: no cross-process keys unless opted in
    for key in ("trace_id", "role", "pid", "ctx"):
        assert key not in meta


# ------------------------------------------------- cross-process stitching
def _two_process_trace(tmp_path):
    """A coordinator file + a worker file linked both ways: file-level
    (worker spawned with the fit-span ctx) and span-level (the worker's
    train span carries the round-span ctx in its attrs)."""
    cpath = str(tmp_path / "coordinator.jsonl")
    wpath = str(tmp_path / "worker0.p1.jsonl")
    coord = Tracer(cpath, clock=FakeClock(), wall=lambda: 1000.0,
                   role="pod-coordinator", trace_id="tid0")
    with coord.span("pod.fit", topology="tree"):
        ctx_spawn = coord.ctx()  # names the fit span
        with coord.span("pod.round", round=0):
            ctx_req = coord.ctx()  # names the round span
    coord.close()
    worker = Tracer(wpath, clock=FakeClock(), wall=lambda: 1000.5,
                    role="pod-worker", ctx=ctx_spawn)
    with worker.span("pod.leaf_load", leaf=0):
        pass
    with worker.span("pod.leaf_train", round=0,
                     ctx=ctx_req.to_dict()):
        pass
    worker.close()
    return cpath, wpath


def test_cross_process_reparenting(tmp_path):
    cpath, wpath = _two_process_trace(tmp_path)
    recs = merge_trace_files([cpath, wpath])
    spans, roles = cross_process_spans(recs)
    assert roles == ["pod-coordinator", "pod-worker"]
    by_name = {s["name"]: s for s in spans}
    fit, rnd = by_name["pod.fit"], by_name["pod.round"]
    load, train = by_name["pod.leaf_load"], by_name["pod.leaf_train"]
    # the worker inherited its ctx= trace_id, so the origin index
    # resolves both links into the coordinator's file
    assert load["_gparent"] == fit["_gid"]      # file-level (meta ctx)
    assert train["_gparent"] == rnd["_gid"]     # span-level (attrs ctx)
    assert rnd["_gparent"] == fit["_gid"]       # plain local parentage
    assert fit["_gparent"] is None
    assert load["_role"] == "pod-worker" and fit["_role"] == "pod-coordinator"

    stats = reparent_stats(recs)
    assert stats == {"files": 2, "roles": roles, "spans": 4,
                     "reparented": 2, "unresolved": 0}

    timeline = format_timeline(recs)
    assert "pod-coordinator" in timeline and "pod-worker" in timeline
    # resolved depth: the train span indents under coordinator spans
    train_line = next(ln for ln in timeline.splitlines()
                      if "pod.leaf_train" in ln)
    assert "    pod.leaf_train" in train_line  # depth >= 2
    gantt = format_round_gantt(recs)
    assert "#" in gantt and "round" in gantt

    body = render_report(recs)
    assert "cross-process timeline" in body
    assert "2 spans re-parented, 0 unresolved" in body


def test_single_roleless_file_degrades_to_local_report(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with Tracer(path, clock=FakeClock(), wall=lambda: 0.0) as tr:
        with tr.span("training"):
            pass
    recs = merge_trace_files([path])
    _, roles = cross_process_spans(recs)
    assert roles == ["main"]
    assert "cross-process timeline" not in render_report(recs)


def test_unresolved_contexts_are_counted_not_invented(tmp_path):
    cpath, wpath = _two_process_trace(tmp_path)
    # merge the worker file ALONE: its contexts name a file that is not
    # in the merged set, so nothing re-parents and both its root spans
    # count as unresolved — the --smoke / chaos-gate failure signal
    recs = merge_trace_files([wpath])
    stats = reparent_stats(recs)
    assert stats["reparented"] == 0
    assert stats["unresolved"] == 2


# ------------------------------------------------------- fleet aggregation
def _payload_with(role, instance, pid=None, status=None, **counters):
    reg = MetricsRegistry()
    for name, val in counters.items():
        reg.counter(name.replace("__", ".")).inc(val)
    return snapshot_payload(role, instance, reg.snapshot(), pid=pid,
                            status=status)


def test_merge_fleet_tags_origin_and_conserves_totals():
    p1 = _payload_with("serve", "r-1", serve__ok=3)
    p2 = _payload_with("serve", "r-2", serve__ok=4)
    merged = merge_fleet([p1, p2])
    entries = [e for e in merged["metrics"] if e["name"] == "serve.ok"]
    # label-disjoint after tagging: one series per process, sum exact
    assert {e["labels"]["instance"] for e in entries} == {"r-1", "r-2"}
    assert all(e["labels"]["role"] == "serve" for e in entries)
    assert sum(e["value"] for e in entries) == 7
    assert merge_fleet([]) == {"v": 1, "metrics": []}


def test_fleet_labels_beat_process_local_labels():
    reg = MetricsRegistry()
    reg.counter("serve.ok", instance="imposter").inc(2)
    p = snapshot_payload("serve", "r-real", reg.snapshot())
    merged = merge_fleet([p])
    (entry,) = [e for e in merged["metrics"] if e["name"] == "serve.ok"]
    # the collector's identity assignment wins, or two processes could
    # alias one series and double-count
    assert entry["labels"]["instance"] == "r-real"


def test_fleet_collector_rates_and_merge_parity():
    count = {"n": 0}

    def src():
        return _payload_with("serve", "r-1", pid=1, serve__ok=count["n"])

    def dead():
        raise OSError("connection refused")

    clk = iter([10.0, 12.0])
    coll = FleetCollector(clock=lambda: next(clk))
    coll.add_callable(src, name="r-1")
    coll.add_callable(dead, name="dead")
    count["n"] = 5
    v1 = coll.scrape_once()
    assert coll.rates() == {}  # no deltas until a second scrape
    assert "OSError" in v1.errors["dead"]
    count["n"] = 9
    v2 = coll.scrape_once()
    # qps = counter delta / clock delta = (9-5)/(12-10)
    assert coll.rates() == {("serve", "r-1"): {"qps": 2.0, "serve.ok": 2.0}}
    # the acceptance contract: the published merged view IS the fold of
    # the per-process payloads, exactly
    assert v2.merged == merge_fleet(v2.processes)
    assert coll.view() is v2
    assert render_fleet_text(v2).startswith(
        "# fleet: 1 process(es), 1 error(s)")


def test_snapshot_file_roundtrip_and_payload_gates(tmp_path):
    p = _payload_with("autopilot", "ap-1", pid=7, serve__ok=1)
    path = str(tmp_path / "drop.json")
    write_snapshot_file(path, p)
    assert read_snapshot_file(path) == p
    # version / shape gates
    with pytest.raises(ValueError, match="v"):
        parse_payload({**p, "v": 999})
    with pytest.raises(ValueError, match="role"):
        parse_payload({"v": 1, "instance": "x", "snapshot": p["snapshot"]})
    with pytest.raises(ValueError):
        parse_payload("not a dict")
    # an unsupported registry snapshot is refused at payload build time
    with pytest.raises(ValueError, match="snapshot version"):
        snapshot_payload("serve", "r-1", {"v": 99, "metrics": []})


def test_format_top_golden():
    p1 = _payload_with(
        "serve", "r-1", pid=42,
        status={"models": {"m": {"generation": 3, "breaker": "closed",
                                 "p99_s": 0.0123, "burning": False}}},
        serve__ok=7)
    reg = MetricsRegistry()
    reg.counter("pod.worker_requests").inc(5)
    reg.gauge("pod.live_shards").set(2)
    p2 = snapshot_payload("pod-worker", "w0", reg.snapshot(), pid=43)
    view = FleetView([p1, p2], {}, merge_fleet([p1, p2]), 12.0)
    rows = top_rows(view, rates={("serve", "r-1"): {"qps": 2.5}})
    text = format_top(rows, errors={"http://dead": "URLError: x"},
                      clock_s=12.0)
    assert text == (
        "tpusvm fleet — 2 process(es) — t=12.0s\n"
        "ROLE        INSTANCE  PID  GEN  REQS  QPS  P99MS  BURN  BREAKER  SHARDS\n"
        "pod-worker  w0        43   -    5     -    -      -     -        2\n"
        "serve       r-1       42   3    7     2.5  12.3   no    closed   -\n"
        "! http://dead: URLError: x\n"
    )


def test_fleet_collector_thread_lifecycle():
    coll = FleetCollector()
    coll.add_callable(lambda: _payload_with("serve", "r-1", serve__ok=1),
                      name="r-1")
    with coll:
        coll.start(interval_s=60.0)  # first scrape is synchronous
        assert coll.view() is not None
        assert coll._thread is not None and coll._thread.daemon
        with pytest.raises(RuntimeError, match="already started"):
            coll.start()
    assert coll._thread is None  # stop() joined and cleared it


# ------------------------------------------------ serve + router transport
def test_serve_http_exports_fleet_payload_and_traced_spans(tmp_path):
    jnp = pytest.importorskip("jax.numpy")
    from tpusvm.config import SVMConfig
    from tpusvm.data import rings
    from tpusvm.models import BinarySVC
    from tpusvm.serve import ServeConfig, Server
    from tpusvm.serve.http import make_http_server, start_http_thread

    X, Y = rings(n=96, seed=1)
    model = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float32).fit(X, Y)
    srv = Server(ServeConfig(max_batch=8), dtype=jnp.float32)
    srv.add_model("m", model)
    srv.warmup()
    tracer = Tracer(str(tmp_path / "serve.jsonl"), role="serve")
    httpd = make_http_server(srv, port=0)
    httpd.tpusvm_tracer = tracer
    srv.attach_http(httpd, start_http_thread(httpd))
    host, port = httpd.server_address[:2]
    base = f"http://{host}:{port}"
    ctx = TraceContext(trace_id="deadbeef", span_id=7, role="router", pid=1)
    try:
        with urllib.request.urlopen(base + "/metrics.json",
                                    timeout=10) as resp:
            payload = parse_payload(json.loads(resp.read()))
        assert payload["role"] == "serve"
        assert payload["instance"] == srv.replica_id

        body = json.dumps(
            {"instances": np.asarray(X[:2], float).tolist()}).encode()
        req = urllib.request.Request(
            base + "/v1/models/m:predict", data=body,
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: ctx.to_header()}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
    finally:
        srv.close()
        tracer.close()
    spans = [r for r in read_trace(tracer.path) if r["kind"] == "span"]
    (span,) = [s for s in spans if s["name"] == "serve.request"]
    assert span["attrs"]["model"] == "m" and span["attrs"]["rows"] == 2
    # the propagated context landed verbatim — this is what the merged
    # report re-parents the request under
    assert span["attrs"]["ctx"] == ctx.to_dict()


def _replica_health(url, timeout_s=0.0):
    return {"status": "ok", "replica_id": "r-x", "uptime_s": 1.0,
            "models": {"m": "closed"}, "swap": {"m": {"generation": 1}},
            "slo": {}}


def test_router_injects_trace_header_and_keeps_3arg_transport(tmp_path):
    from tpusvm.router import Router, RouterConfig

    calls = []

    def transport4(url, body, timeout_s, headers):
        calls.append((url, headers))
        return 200, b"{}", None

    tracer = Tracer(str(tmp_path / "router.jsonl"), role="router",
                    trace_id="rtid")
    r = Router(RouterConfig(replicas=("http://a",), replication=1,
                            poll_interval_s=10.0),
               transport=transport4, fetch=_replica_health,
               registry=MetricsRegistry(), log_fn=None, tracer=tracer)
    r.poller.poll_once()
    inbound = TraceContext(trace_id="cli", span_id=2, role="client", pid=5)
    code, _, _ = r.forward("m", b"{}", ctx=inbound)
    assert code == 200
    (_, headers), = calls
    out = TraceContext.from_header(headers[TRACE_HEADER])
    # the outbound context is the ROUTER's (minted inside router.forward),
    # not the inbound one passed through — replicas parent into the
    # router's timeline
    assert out.role == "router" and out.trace_id == "rtid"
    assert out.span_id is not None
    r.poller.stop()
    tracer.close()
    spans = [rec for rec in read_trace(tracer.path)
             if rec["kind"] == "span"]
    (fwd,) = [s for s in spans if s["name"] == "router.forward"]
    assert fwd["attrs"]["ctx"] == inbound.to_dict()
    assert fwd["id"] == out.span_id

    # a tracer-less router calls the legacy 3-arg transport form
    calls3 = []

    def transport3(url, body, timeout_s):
        calls3.append(url)
        return 200, b"{}", None

    r2 = Router(RouterConfig(replicas=("http://a",), replication=1,
                             poll_interval_s=10.0),
                transport=transport3, fetch=_replica_health,
                registry=MetricsRegistry(), log_fn=None)
    r2.poller.poll_once()
    code, _, _ = r2.forward("m", b"{}")
    assert code == 200 and calls3
    payload = parse_payload(r2.fleet_payload())
    assert payload["role"] == "router"
    assert payload["instance"].startswith("router-")
    r2.poller.stop()


# ------------------------------------------------------------- benchdiff
def test_benchdiff_knows_the_obs_fabric_schema():
    from tpusvm.obs.benchdiff import diff_records

    base = {"bench": "obs_fabric", "topology": "tree", "P": 4, "n": 512,
            "smoke": False, "bit_identical": True, "reparented_ok": True,
            "report_ok": True, "converged": True, "sv_count": 40,
            "rounds": 3, "unresolved_spans": 0, "overhead_frac": 0.02,
            "t_off_s": 1.0, "t_on_s": 1.02, "violations": []}
    broken = dict(base, bit_identical=False, reparented_ok=False,
                  unresolved_spans=7, overhead_frac=0.2,
                  violations=["traced fit is not bit-identical"])
    res = diff_records([base], [broken], level="full")
    bad = {f.metric for f in res.regressions}
    assert {"bit_identical", "reparented_ok", "unresolved_spans",
            "overhead_frac", "violations"} <= bad

    # overhead columns are timing rules: a slow CI box must not fail
    # the smoke gate, but the full gate still catches it
    slow = dict(base, overhead_frac=0.5, t_on_s=9.0)
    assert diff_records([base], [slow], level="smoke").ok
    full = diff_records([base], [slow], level="full")
    assert {"overhead_frac", "t_on_s"} <= {f.metric
                                           for f in full.regressions}
    # identical artifacts pass at both levels
    assert diff_records([base], [dict(base)], level="full").ok
