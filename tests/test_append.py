"""Crash-safe tail-shard append ingest (tpusvm/stream/append.py).

The contract under test: ShardWriter.open_append grows a committed
dataset BIT-IDENTICALLY to a one-shot ingest of the concatenated data
(shard layout, per-shard stats, manifest JSON — including the merged
feature min/max, the reopen close() bug), with exactly-once semantics
under a kill at EVERY journal/shard/commit transition, and divergent
replays rejected rather than silently applied.
"""

import json
import os

import numpy as np
import pytest

from tpusvm import faults
from tpusvm.data import rings
from tpusvm.status import StreamStatus
from tpusvm.stream import (
    AppendError,
    ShardWriter,
    ingest_arrays,
    open_dataset,
)

X, Y = rings(n=300, seed=11)

BATCHES = [(s, min(300, s + 40)) for s in range(150, 300, 40)]


def _one_shot(tmp_path, name="ref"):
    return ingest_arrays(str(tmp_path / name), X, Y, rows_per_shard=64)


def _prefix(tmp_path, name):
    out = str(tmp_path / name)
    ingest_arrays(out, X[:150], Y[:150], rows_per_shard=64)
    return out


def _append_session(out, resume=False):
    w = ShardWriter.open_append(out, resume=resume)
    for a, b in BATCHES:
        w.append(X[a:b], Y[a:b])
    return w.close()


def _manifest_json(m):
    return json.dumps(m.to_json(), sort_keys=True)


# ------------------------------------------------- one-shot bit-parity
def test_append_matches_one_shot_ingest_bitwise(tmp_path):
    """The headline parity claim, which subsumes the reopen-close()
    min/max merge bugfix: the manifest JSON (per-shard stats, checksums,
    global min/max via merged stats) is byte-equal to a one-shot ingest
    of the concatenation — a tail-only stats refit could not pass."""
    ref = _one_shot(tmp_path)
    out = _prefix(tmp_path, "grown")
    m = _append_session(out)
    assert _manifest_json(m) == _manifest_json(ref)
    ds = open_dataset(out)
    assert all(s == StreamStatus.OK for s in ds.validate())
    Xr, Yr = ds.load_arrays()
    assert np.array_equal(Xr, X) and np.array_equal(Yr, Y)
    # the merged scaler == a full-array fit (the min/max merge pin)
    st = ds.stats()
    assert np.array_equal(st.min_val, X.min(axis=0))
    assert np.array_equal(st.max_val, X.max(axis=0))


def test_append_preserves_prefix_row_order(tmp_path):
    """The prefix-extension contract refresh/assign enforce by name:
    the original dataset's global row order is a strict prefix of the
    grown dataset's."""
    out = _prefix(tmp_path, "g2")
    before = open_dataset(out).load_arrays()
    _append_session(out)
    after = open_dataset(out).load_arrays()
    n0 = len(before[0])
    assert np.array_equal(after[0][:n0], before[0])
    assert np.array_equal(after[1][:n0], before[1])


def test_append_full_shard_tail_and_default_rows_per_shard(tmp_path):
    """A dataset whose last shard is exactly full appends without
    touching any existing file (no tail adoption)."""
    ref = ingest_arrays(str(tmp_path / "r"), X[:256], Y[:256],
                        rows_per_shard=64)
    out = str(tmp_path / "g")
    ingest_arrays(out, X[:192], Y[:192], rows_per_shard=64)
    w = ShardWriter.open_append(out)   # rows_per_shard derived: 64
    w.append(X[192:256], Y[192:256])
    m = w.close()
    assert _manifest_json(m) == _manifest_json(ref)


def test_open_append_validation(tmp_path):
    with pytest.raises(FileNotFoundError, match="not a committed"):
        ShardWriter.open_append(str(tmp_path / "nope"))
    out = _prefix(tmp_path, "v")
    with pytest.raises(AppendError, match="rows_per_shard"):
        ShardWriter.open_append(out, rows_per_shard=32)
    w = ShardWriter.open_append(out)
    with pytest.raises(ValueError, match="feature count"):
        w.append(np.zeros((4, 5)), np.ones(4, np.int32))


def test_append_empty_session_is_a_noop(tmp_path):
    out = _prefix(tmp_path, "noop")
    before = _manifest_json(open_dataset(out).manifest)
    w = ShardWriter.open_append(out)
    m = w.close()
    assert _manifest_json(m) == before
    assert all(s == StreamStatus.OK
               for s in open_dataset(out).validate())


# --------------------------------------------- exactly-once under kill
def _count_hits(tmp_path, point):
    out = _prefix(tmp_path, f"count_{point.replace('.', '_')}")
    plan = faults.FaultPlan([], seed=0)
    with faults.active(plan):
        _append_session(out)
    return plan.hits(point)


@pytest.mark.parametrize("point", ["stream.append", "ingest.write_shard"])
def test_append_kill_at_every_journal_transition(tmp_path, point):
    """Mirror of test_faults' kill-resume pattern, over EVERY hit of
    the append session's injection points (journal writes, the commit's
    rename and journal-delete transitions, every staged shard write):
    kill there, resume with the replayed batch stream, and the result
    is row-set AND checksum identical to the one-shot reference, with
    the journal gone."""
    ref_j = _manifest_json(_one_shot(tmp_path))
    hits = _count_hits(tmp_path, point)
    assert hits >= 3, f"{point} fired only {hits} times — vacuous sweep"
    for k in range(1, hits + 1):
        out = _prefix(tmp_path, f"k_{point.replace('.', '_')}_{k}")
        plan = faults.FaultPlan(
            [faults.FaultRule(point=point, kind="kill", at_hit=k)],
            seed=0)
        with pytest.raises(faults.SimulatedKill):
            with faults.active(plan):
                _append_session(out)
        m = _append_session(out, resume=True)
        assert _manifest_json(m) == ref_j, f"{point} kill at hit {k}"
        ds = open_dataset(out)
        assert all(s == StreamStatus.OK for s in ds.validate())
        assert not os.path.exists(os.path.join(out,
                                               "ingest.journal.json"))


def test_append_transient_journal_writes_are_retried(tmp_path):
    ref_j = _manifest_json(_one_shot(tmp_path))
    out = _prefix(tmp_path, "tr")
    plan = faults.FaultPlan(
        [faults.FaultRule(point="stream.append", kind="transient",
                          max_hits=2)], seed=0)
    with faults.active(plan):
        m = _append_session(out)
    assert _manifest_json(m) == ref_j


def test_append_divergent_replay_rejected(tmp_path):
    """The duplicate/divergent-append guard: a resumed session replaying
    a batch whose content CRC differs from the journal ledger is an
    AppendError, never silent corruption."""
    out = _prefix(tmp_path, "div")
    plan = faults.FaultPlan(
        [faults.FaultRule(point="stream.append", kind="kill", at_hit=2)],
        seed=0)
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            _append_session(out)
    w = ShardWriter.open_append(out, resume=True)
    a, b = BATCHES[0]
    with pytest.raises(AppendError, match="divergent"):
        w.append(X[a:b] + 1.0, Y[a:b])


def test_append_resume_without_journal_is_fresh(tmp_path):
    """No journal = nothing to resume (the house resume semantics): the
    session starts fresh and appends normally."""
    ref_j = _manifest_json(_one_shot(tmp_path))
    out = _prefix(tmp_path, "fresh")
    m = _append_session(out, resume=True)
    assert _manifest_json(m) == ref_j


def test_append_second_session_without_resume_refuses_journal(tmp_path):
    out = _prefix(tmp_path, "ref2")
    # kill at the SECOND journal transition, so the first journal write
    # is durable and the directory is visibly a crash site
    plan = faults.FaultPlan(
        [faults.FaultRule(point="stream.append", kind="kill", at_hit=2)],
        seed=0)
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            _append_session(out)
    with pytest.raises(AppendError, match="resume=True"):
        ShardWriter.open_append(out)


def test_append_corrupt_staged_shard_detected_on_resume(tmp_path):
    """A corrupt rule mangling a staged shard's bytes is caught by the
    journal's checksum verification at resume, naming the shard."""
    from tpusvm.stream import ShardError

    out = _prefix(tmp_path, "cor")
    plan = faults.FaultPlan([
        faults.FaultRule(point="ingest.write_shard", kind="corrupt",
                         at_hit=1),
        faults.FaultRule(point="stream.append", kind="kill", at_hit=2),
    ], seed=9)
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            _append_session(out)
    with pytest.raises(ShardError):
        ShardWriter.open_append(out, resume=True)


def test_append_v1_journal_is_refused(tmp_path):
    """A v1 (fresh-ingest) journal in the directory belongs to
    `tpusvm ingest --resume`, not to an append session."""
    from tpusvm.stream import ingest_blocks

    out = str(tmp_path / "v1")
    plan = faults.FaultPlan(
        [faults.FaultRule(point="ingest.write_shard", kind="kill",
                          at_hit=3)], seed=0)
    with pytest.raises(faults.SimulatedKill):
        with faults.active(plan):
            ingest_blocks(out, [(X, Y)], rows_per_shard=64)
    # no manifest yet (fresh ingest died) -> open_append refuses already
    with pytest.raises(FileNotFoundError):
        ShardWriter.open_append(out, resume=True)


def test_append_feeds_refresh_prefix_contract(tmp_path):
    """The closed loop's data half: a model deployed on the prefix
    warm-refreshes on the append-grown dataset (deployed_seed's prefix
    check passes because append IS a prefix extension)."""
    import jax.numpy as jnp

    from tpusvm.config import SVMConfig
    from tpusvm.models import BinarySVC
    from tpusvm.serve.refresh import refresh_fit

    out = _prefix(tmp_path, "loop")
    deployed = str(tmp_path / "dep.npz")
    BinarySVC(SVMConfig(C=10.0, gamma=10.0),
              dtype=jnp.float32).fit(X[:150], Y[:150]).save(deployed)
    _append_session(out)
    Xg, Yg = open_dataset(out).load_arrays()
    model = refresh_fit(deployed, Xg, Yg,
                        out_path=str(tmp_path / "re.npz"))
    assert model.status_.name == "CONVERGED"
    assert model.score(X, Y) > 0.8
