"""Cascade checkpoint/resume (SURVEY.md §5.4).

The reference's inter-round global-SV broadcast is an in-memory checkpoint
(warm-start semantics, C20/C21); these tests cover the persisted version:
state written per round, resumable mid-run, and resume converging to the
same model as an uninterrupted run.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import CascadeConfig, SVMConfig
from tpusvm.data import MinMaxScaler, rings
from tpusvm.parallel.cascade import (
    cascade_fit,
    load_round_state,
    save_round_state,
)
from tpusvm.parallel.svbuffer import empty


CFG = SVMConfig(C=10.0, gamma=10.0)
CC = CascadeConfig(n_shards=4, sv_capacity=64, topology="star")


@pytest.fixture(scope="module")
def data():
    X, Y = rings(n=320, seed=11)
    return MinMaxScaler().fit_transform(X), Y


def test_round_state_roundtrip(tmp_path):
    buf = empty(8, 3)
    buf = buf._replace(
        X=buf.X.at[0].set(1.5),
        Y=buf.Y.at[0].set(1),
        alpha=buf.alpha.at[0].set(0.25),
        ids=buf.ids.at[0].set(42),
        valid=buf.valid.at[0].set(True),
    )
    path = str(tmp_path / "ck.npz")
    save_round_state(path, buf, {42}, rnd=3, b=-1.25)
    loaded, prev_ids, next_round, b = load_round_state(path)
    assert prev_ids == {42} and next_round == 4 and b == -1.25
    np.testing.assert_allclose(np.asarray(loaded.X), np.asarray(buf.X))
    assert np.asarray(loaded.valid).sum() == 1
    assert int(np.asarray(loaded.ids)[0]) == 42


def test_checkpoint_written_every_round(tmp_path, data):
    X, Y = data
    path = str(tmp_path / "cascade.npz")
    res = cascade_fit(X, Y, CFG, CC, checkpoint_path=path)
    assert res.converged
    _, prev_ids, next_round, _ = load_round_state(path)
    assert next_round == res.rounds + 1
    assert prev_ids == set(res.sv_ids.tolist())


def test_resume_matches_uninterrupted(tmp_path, data):
    X, Y = data
    full = cascade_fit(X, Y, CFG, CC)
    assert full.converged and full.rounds >= 2

    # interrupted run: only 1 round, then resume to convergence
    path = str(tmp_path / "cascade.npz")
    short_cfg = dataclasses.replace(CFG, max_rounds=1)
    partial = cascade_fit(X, Y, short_cfg, CC, checkpoint_path=path)
    assert not partial.converged

    resumed = cascade_fit(X, Y, CFG, CC, checkpoint_path=path, resume=True)
    assert resumed.converged
    assert resumed.rounds == full.rounds  # same trajectory, same round count
    assert set(resumed.sv_ids.tolist()) == set(full.sv_ids.tolist())
    assert resumed.b == pytest.approx(full.b, rel=1e-6)


def test_resume_shape_mismatch_raises(tmp_path, data):
    X, Y = data
    path = str(tmp_path / "cascade.npz")
    cascade_fit(X, Y, dataclasses.replace(CFG, max_rounds=1), CC,
                checkpoint_path=path)
    bad_cc = dataclasses.replace(CC, sv_capacity=32)
    with pytest.raises(ValueError, match="checkpoint shapes"):
        cascade_fit(X, Y, CFG, bad_cc, checkpoint_path=path, resume=True)


def test_resume_without_file_starts_fresh(tmp_path, data):
    X, Y = data
    path = str(tmp_path / "missing.npz")
    res = cascade_fit(X, Y, CFG, CC, checkpoint_path=path, resume=True)
    assert res.converged


def test_resume_fingerprint_is_order_free_and_field_sensitive():
    from tpusvm.parallel.cascade import _resume_fingerprint

    fp = _resume_fingerprint(True, 3, {1, 2, 5}, -1.25)
    assert fp.dtype == np.uint32 and fp.shape == (5,)
    np.testing.assert_array_equal(
        fp, _resume_fingerprint(True, 3, {5, 2, 1}, -1.25))
    for other in (
        _resume_fingerprint(False, 3, {1, 2, 5}, -1.25),
        _resume_fingerprint(True, 4, {1, 2, 5}, -1.25),
        _resume_fingerprint(True, 3, {1, 2}, -1.25),
        _resume_fingerprint(True, 3, {1, 2, 5}, -1.25000001),
    ):
        assert not np.array_equal(fp, other)


def test_resume_agreement_check(tmp_path):
    """ADVICE r3 medium: a multi-host resume where processes loaded
    different (or missing) checkpoint state must raise before any round
    collective launches, not deadlock inside one."""
    from tpusvm.parallel.cascade import (
        _check_resume_fingerprints,
        _resume_fingerprint,
        _verify_resume_agreement,
    )

    ok = _resume_fingerprint(True, 2, {7, 9}, 0.5)
    _check_resume_fingerprints(np.stack([ok, ok, ok]))  # agreement: no raise

    missing = _resume_fingerprint(False, 1, set(), 0.0)
    with pytest.raises(RuntimeError, match=r"missing on processes \[1\]"):
        _check_resume_fingerprints(np.stack([ok, missing]))

    divergent = _resume_fingerprint(True, 2, {7, 8}, 0.5)
    with pytest.raises(RuntimeError, match="DIVERGENT"):
        _check_resume_fingerprints(np.stack([ok, divergent]))

    # a load FAILURE travels through the gather as status=2 (raising
    # locally before the gather would hang the peers) and every process
    # raises naming the failing one
    failed = _resume_fingerprint(2, 1, set(), 0.0)
    with pytest.raises(RuntimeError,
                       match=r"failed to load on processes \[1\]"):
        _check_resume_fingerprints(np.stack([ok, failed]))

    # single-process: the in-run check is a no-op (covers the plain-resume
    # tests above passing through it)
    _verify_resume_agreement(True, 2, {7, 9}, 0.5)


def test_resume_roundtrips_alpha_dtype(tmp_path, data):
    # the checkpoint must hand back exactly the inter-round state the live
    # run would carry: load keeps the STORED alpha dtype rather than
    # casting to the feature dtype (extract_svs defines what that stored
    # dtype is — currently the feature dtype, even in mixed precision)
    X, Y = data
    path = str(tmp_path / "ck64.npz")
    res = cascade_fit(X, Y, dataclasses.replace(CFG, max_rounds=1), CC,
                      checkpoint_path=path, accum_dtype=jnp.float64)
    buf, _, _, _ = load_round_state(path, dtype=jnp.float32)
    assert buf.alpha.dtype == res.sv_alpha.dtype
    assert buf.X.dtype == jnp.float32
    # and a hand-written f64 buffer survives the roundtrip untruncated
    b64 = empty(4, 2)._replace(alpha=jnp.zeros(4, jnp.float64))
    save_round_state(path, b64, set(), rnd=1, b=0.0)
    loaded, _, _, _ = load_round_state(path, dtype=jnp.float32)
    assert loaded.alpha.dtype == jnp.float64
