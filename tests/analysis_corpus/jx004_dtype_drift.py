# known-bad: dtype drift from implicit-dtype constructors (JX004)
import jax
import jax.numpy as jnp


@jax.jit
def accumulate(x):
    acc = jnp.zeros(x.shape)  # JX004: no dtype= — depends on x64 flip
    scale = jnp.array(0.5)  # JX004: bare float literal
    steps = jnp.arange(8)  # JX004: no dtype=
    return acc + scale * x + steps.sum()
