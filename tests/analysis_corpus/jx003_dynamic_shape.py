# known-bad: data-dependent output shapes under jit (JX003)
import jax
import jax.numpy as jnp


@jax.jit
def support_vectors(alpha, X):
    live = alpha > 0
    sv_rows = X[live]  # JX003: boolean-mask indexing
    idx = jnp.where(alpha > 0)  # JX003: one-arg jnp.where
    labels = jnp.unique(alpha)  # JX003: unique without size=
    return sv_rows, idx, labels, X[alpha > 0]  # JX003: inline mask
