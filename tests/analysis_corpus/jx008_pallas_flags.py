# known-bad: pallas_* flags the resolved solver config ignores (JX008)
from tpusvm.solver.blocked import blocked_smo_solve


def mislabeled_ab_run(X, Y):
    # JX008: eta_exclude recorded while the XLA engine runs
    return blocked_smo_solve(X, Y, inner="xla", wss=2,
                             pallas_eta_exclude=True)


def wrong_selection_order(X, Y):
    # JX008: multipair is a first-order (wss=1) kernel
    return blocked_smo_solve(X, Y, inner="pallas", wss=2,
                             pallas_multipair=2)


def layout_without_kernel(X, Y):
    # JX008: layout only reaches the pallas engine
    return blocked_smo_solve(X, Y, inner="xla", pallas_layout="flat")
