"""Known-good concurrency corpus: a thread-owning class doing everything
the JXC rules demand — guarded shared writes, one global lock order,
no blocking under locks, daemon worker + join ownership on close, timed
waits with checked results, Condition.wait in a predicate loop."""

import queue
import threading


class GoodWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._q = queue.Queue(maxsize=8)
        self._done = threading.Event()
        self.count = 0
        self.items = []
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        while not self._done.is_set():
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            with self._lock:
                self.count += 1
                self.items.append(item)

    def submit(self, item, timeout=1.0):
        self._q.put(item, timeout=timeout)

    def wait_quiet(self, n, timeout=1.0):
        with self._cond:
            while self.count < n:
                if not self._cond.wait(timeout):
                    return False
        return True

    def close(self, timeout=1.0):
        self._done.set()
        self._t.join(timeout=timeout)
        if not self._done.wait(timeout):
            raise RuntimeError("worker did not acknowledge shutdown")
