"""JXC202 corpus: two methods acquire the same pair of locks in
opposite orders — two threads on the opposing paths deadlock."""

import threading


class Transfer:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.x = 0
        self.y = 0

    def a_then_b(self):
        with self._a:
            with self._b:  # BAD: A -> B here ...
                self.x += 1

    def b_then_a(self):
        with self._b:
            with self._a:  # BAD: ... B -> A there
                self.y += 1
