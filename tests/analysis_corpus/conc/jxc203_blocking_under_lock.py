"""JXC203 corpus: a blocking call (time.sleep) inside a guarded region
— every thread contending for the lock stalls behind the sleeper."""

import threading
import time


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self.last = 0.0

    def tick(self):
        with self._lock:
            time.sleep(0.1)  # BAD: blocks while holding the lock
            self.last = time.monotonic()
