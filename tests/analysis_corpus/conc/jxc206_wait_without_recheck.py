"""JXC206 corpus: timed Event.wait with the result discarded — on
timeout the event is NOT set, but execution proceeds as if it were."""

import threading


class Gate:
    def __init__(self):
        self._ready = threading.Event()

    def open(self):
        self._ready.set()

    def wait_ready(self):
        self._ready.wait(1.0)  # BAD: timeout result ignored
        return True
