"""JXC205 corpus: thread created without daemon= and never joined — it
outlives interpreter shutdown intent and leaks past test teardown."""

import threading


def launch(fn):
    t = threading.Thread(target=fn)  # BAD: no daemon=, no join ownership
    t.start()
    return t
