"""JXC204 corpus: non-atomic check-then-act. The predicate is read
under the lock, the decision is taken OUTSIDE it, and the write happens
under a fresh acquisition — the state may have changed in between."""

import threading


class Budget:
    def __init__(self):
        self._lock = threading.Lock()
        self.remaining = 10

    def take(self):
        with self._lock:
            ok = self.remaining > 0
        if ok:
            with self._lock:  # BAD: reacquired; `remaining` may be 0 now
                self.remaining -= 1
        return ok
