"""JXC201 corpus: shared attribute written outside any lock in a
thread-spawning class. The worker mutates `self.count` with no guard
while clients can read/write it concurrently."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        for _ in range(100):
            self.count += 1  # BAD: unguarded write to shared state
