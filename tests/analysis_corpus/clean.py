# known-GOOD: the TPU-native versions of every corpus hazard; the linter
# must report nothing here (tests/test_analysis.py::test_clean_corpus).
import functools

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.jit, static_argnames=("mode", "steps"))
def solve(x, y, mode="fast", steps=8):
    if mode == "fast":  # fine: static argname
        y = y * 2.0
    if x is None:  # fine: trace-time identity check
        return y
    n = x.shape[0]
    if n > 128:  # fine: shapes are static under jit
        y = y[:128]
    acc = jnp.zeros((n,), jnp.float32)  # fine: explicit dtype
    mask = x > 0
    pos = jnp.where(mask, x, 0.0)  # fine: three-arg where
    branch = lax.cond(x.sum() > 0, lambda a: a, lambda a: -a, pos)
    for _ in range(steps):  # fine: static Python loop bound
        acc = acc + branch
    return acc


def host_driver(batches):
    results = [solve(b, b) for b in batches]
    # fine: one sync after the loop, not one per iteration
    return [r.block_until_ready() for r in results][-1]
