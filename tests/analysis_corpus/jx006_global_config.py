# known-bad: mutated module-global config read under tracing (JX006)
import jax

GAMMA = 0.00125


def set_gamma(g):
    global GAMMA
    GAMMA = g


@jax.jit
def kernel_row(d2):
    return jax.numpy.exp(-GAMMA * d2)  # JX006: frozen at first trace
