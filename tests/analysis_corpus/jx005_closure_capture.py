# known-bad: jitted function closing over a module-level ndarray (JX005)
import jax
import numpy as np

PROJECTION = np.random.randn(1024, 1024)


@jax.jit
def project(x):
    return x * PROJECTION  # JX005: constant-folded into the jaxpr
