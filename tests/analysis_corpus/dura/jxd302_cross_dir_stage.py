"""JXD302 corpus: the temp is staged under tempfile's directory while
the rename target lives in the caller's output directory. When /tmp and
the data volume are different filesystems, os.replace raises EXDEV —
and any fallback degrades to copy+delete, which is not atomic."""

import json
import os
import tempfile


def commit_report(out_dir, payload):
    tmp = os.path.join(tempfile.gettempdir(), "report.json.tmp")
    with open(tmp, "w") as f:
        json.dump(payload, f)
    # BAD: staged in tempfile's dir, committed into out_dir
    os.replace(tmp, os.path.join(out_dir, "report.json"))
