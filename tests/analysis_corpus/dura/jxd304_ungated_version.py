# tpusvm: durable-protocol
"""JXD304 corpus: the writer stamps format_version but the module's
reader never gates it — a file written by a different build half-parses
(or KeyErrors on whichever field moved) instead of failing with a
version error that names the mismatch."""

import json
import os

from tpusvm import faults

FORMAT_VERSION = 3


def save_table(path, rows):
    faults.point("models.save", path=path)
    # BAD: "format_version" is written but load_table never checks it
    obj = {"format_version": FORMAT_VERSION, "rows": rows}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def load_table(path):
    with open(path) as f:
        obj = json.load(f)
    return obj["rows"]
