"""JXD301 corpus: a durable artifact written straight onto its final
path. A kill (or ENOSPC) mid-`json.dump` leaves a torn half-file under
the committed name — the next reader gets a JSONDecodeError instead of
the previous complete artifact."""

import json


def save_report(path, payload):
    with open(path, "w") as f:  # BAD: no staged temp + os.replace
        json.dump(payload, f)
        f.write("\n")
