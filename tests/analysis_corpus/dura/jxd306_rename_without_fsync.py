# tpusvm: durable-protocol=kill-safe
"""JXD306 corpus: a kill-safe protocol committing with a bare
os.replace. The filesystem may journal the rename before the staged
file's data blocks reach disk, so a power loss can commit the NAME of
a hollow file — flush+fsync the staged bytes first (the sanctioned
spelling is tpusvm.utils.durable.fsync_replace)."""

import json
import os

from tpusvm import faults


def commit_journal(path, obj):
    faults.point("stream.journal", path=path)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)  # BAD: rename can outrun the staged bytes
