# tpusvm: durable-protocol
"""JXD303 corpus: a durable-state commit with no faults.point in its
enclosing function (the chaos plans and the derived crash-window matrix
cannot see it), plus a point literal naming an unregistered point."""

import json
import os

from tpusvm import faults


def commit_state(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)  # BAD: no faults.point guards this commit


def tick():
    # BAD: not in faults/injection.py POINTS — an active plan would
    # reject it at the call site
    faults.point("no.such.point")
