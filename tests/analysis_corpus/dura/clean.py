# tpusvm: durable-protocol=kill-safe
"""Known-good durability corpus: everything the JXD rules demand —
staged temps committed with fsync_replace in the target's directory,
every commit behind a registered fault point, the version field gated
by the reader, and the journal deleted only after its artifact lands."""

import json
import os

from tpusvm import faults
from tpusvm.utils.durable import fsync_replace

STATE_VERSION = 2


def save_state(path, payload):
    faults.point("autopilot.state", path=path)
    obj = {"state_version": STATE_VERSION, **payload}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.write("\n")
    fsync_replace(tmp, path)


def load_state(path):
    with open(path) as f:
        obj = json.load(f)
    if obj.get("state_version") != STATE_VERSION:
        raise ValueError(f"unsupported state_version in {path!r}")
    return obj


def commit_session(out_dir, manifest):
    faults.point("stream.journal", commit=True)
    tmp = os.path.join(out_dir, "manifest.json.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    fsync_replace(tmp, os.path.join(out_dir, "manifest.json"))
    # the manifest supersedes the journal: delete last
    journal_path = os.path.join(out_dir, "journal.json")
    if os.path.exists(journal_path):
        os.remove(journal_path)
