"""JXD305 corpus: the recovery journal is deleted BEFORE the artifact
it covers is committed. A kill between the delete and the rename leaves
an uncommitted directory whose journal — the only way to resume — is
already gone: commit first, delete the journal last."""

import json
import os


def commit_session(out_dir, manifest):
    journal_path = os.path.join(out_dir, "journal.json")
    os.remove(journal_path)  # BAD: journal gone, commit still pending
    tmp = os.path.join(out_dir, "manifest.json.tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(out_dir, "manifest.json"))
