# JX010: a raw contraction outside tpusvm/ops and tpusvm/kernels — the
# `@` emits a dot_general with jax's DEFAULT precision (raw single-pass
# bf16 on TPU MXUs) because no precision resolver ever saw it. The jnp
# call form is equally unrouted. Both must route through the kernel
# dispatch / ops.rbf.matmul_p.
import jax
import jax.numpy as jnp


@jax.jit
def f_update(f, K, coef):
    df = K @ coef
    return f + df


@jax.jit
def scores(K, coef, b):
    return jnp.dot(K, coef) - b
