"""JXIR103 corpus — a while-loop carry initialised from a bare Python
int: the counter slot enters the loop as a WEAK int32 aval, so jax must
run its weak-type fixpoint re-trace and the carry dtype is decided by
promotion, not by the code — exactly what shrink-compaction /
checkpoint-resume re-entry (which rebuilds carries from saved avals)
cannot tolerate."""

import jax
import jax.numpy as jnp
from jax import lax

from tpusvm.analysis.ir.entrypoints import IREntryPoint

RULE = "JXIR103"


def _build():
    def count_rounds(x):
        def cond(c):
            return c[0] < 8

        def body(c):
            i, s = c
            return i + 1, s * 0.5 + 1.0

        # BAD: carry slot 0 starts as Python int 0 -> weak int32
        return lax.while_loop(cond, body, (0, jnp.float32(0.0)))

    return count_rounds, (jax.ShapeDtypeStruct((8,), jnp.float32),), {}


ENTRY = IREntryPoint(
    name="corpus.jxir103_weak_carry",
    build=_build,
    description="while carry seeded from a bare Python int",
)
