"""JXIR101 corpus — a contraction that never went through the precision
resolver: `K @ coef` emits a dot_general with precision=None, which on
TPU MXUs means raw single-pass bf16 passes over f32 operands (the exact
footgun config.resolve_matmul_precision closes at the ops layer)."""

import jax
import jax.numpy as jnp

from tpusvm.analysis.ir.entrypoints import IREntryPoint

RULE = "JXIR101"


def _build():
    def f_update(K, coef):
        # BAD: raw matmul — no precision routed to the IR
        return K @ coef

    s = jax.ShapeDtypeStruct
    return f_update, (s((1024, 256), jnp.float32),
                      s((256, 128), jnp.float32)), {}


ENTRY = IREntryPoint(
    name="corpus.jxir101_unrouted_dot",
    build=_build,
    description="raw K @ coef contraction, precision unrouted",
)
