"""JXIR104 corpus — a contraction whose CONTRACTED dimension (130) sits
off the TPU tile grid: the compiler pads every 130-wide operand tile to
the next lane/sublane multiple and the padding cost is paid on every
output tile of the contraction loop. Precision is explicitly routed so
only the alignment rule fires (single-hazard corpus discipline)."""

import jax
import jax.numpy as jnp

from tpusvm.analysis.ir.entrypoints import IREntryPoint

RULE = "JXIR104"


def _build():
    def gram(xa, xb):
        # BAD: d=130 contracting dim — not a multiple of 128 (lane) on
        # the lhs nor of 8 (sublane) on the rhs
        return jnp.matmul(xa, xb, precision="highest")

    s = jax.ShapeDtypeStruct
    return gram, (s((256, 130), jnp.float32),
                  s((130, 256), jnp.float32)), {}


ENTRY = IREntryPoint(
    name="corpus.jxir104_misaligned_tile",
    build=_build,
    description="contracting dim 130 off the (8, 128) tile grid",
)
