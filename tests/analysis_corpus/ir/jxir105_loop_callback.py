"""JXIR105 corpus — a host callback reachable from a compiled loop body
at IR level: the debug print hides inside a helper function, so JX009's
AST walker (which inspects the combinator body's own nodes) has nothing
to flag — but the traced while body carries a debug_callback equation
all the same: one device->host round trip per iteration."""

import jax
import jax.numpy as jnp
from jax import lax

from tpusvm.analysis.ir.entrypoints import IREntryPoint

RULE = "JXIR105"


def _log_gap(gap):
    # the indirection that blinds the AST rule
    jax.debug.print("gap={g}", g=gap)
    return gap


def _build():
    def solve(f):
        def cond(c):
            return c[0] < jnp.int32(8)

        def body(c):
            i, s = c
            # BAD (semantically): helper inserts a per-iteration callback
            return i + jnp.int32(1), s + _log_gap(jnp.max(f))

        return lax.while_loop(
            cond, body, (jnp.int32(0), jnp.float32(0.0)))

    return solve, (jax.ShapeDtypeStruct((128,), jnp.float32),), {}


ENTRY = IREntryPoint(
    name="corpus.jxir105_loop_callback",
    build=_build,
    description="host callback smuggled into a while body via a helper",
)
