"""JXIR106 corpus — a hyperparameter baked into the trace: the builder
captures C in a closure instead of passing it as a traced argument, so
its concrete value lands in the jaxpr as a literal and every sweep
value compiles a fresh executable (the weak-scalar recompile hazard
jit's cache-by-weak-type rule exists to prevent)."""

import jax
import jax.numpy as jnp

from tpusvm.analysis.ir.entrypoints import IREntryPoint

RULE = "JXIR106"


def _build(C=10.0):
    def clip_box(alpha):
        # BAD: C is a Python closure constant, not a traced argument —
        # its VALUE is a literal in the jaxpr
        return jnp.clip(alpha, 0.0, C)

    return clip_box, (jax.ShapeDtypeStruct((1024,), jnp.float32),), {}


ENTRY = IREntryPoint(
    name="corpus.jxir106_baked_scalar",
    build=_build,
    sweep={"C": (10.0, 3.0)},
    description="hyperparameter closure-baked into the trace",
)
