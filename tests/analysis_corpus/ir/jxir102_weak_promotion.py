"""JXIR102 corpus — a Python-scalar-derived ARRAY in the traced graph:
broadcasting the weak hyperparameter scalar materialises a weak-typed
(128, 128) aval whose dtype follows promotion accidents instead of a
declared dtype (and whose weakness would force jax's fixpoint re-trace
if it reached a loop carry)."""

import jax
import jax.numpy as jnp

from tpusvm.analysis.ir.entrypoints import IREntryPoint

RULE = "JXIR102"


def _build(c=2.0):
    def shift(x):
        # BAD: weak scalar broadcast into a weak-typed array aval
        bias = jnp.broadcast_to(c, (128, 128))
        return x + bias

    return shift, (jax.ShapeDtypeStruct((128, 128), jnp.float32),), {}


ENTRY = IREntryPoint(
    name="corpus.jxir102_weak_promotion",
    build=_build,
    description="weak Python scalar broadcast to a weak-typed array",
)
