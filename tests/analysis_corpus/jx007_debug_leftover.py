# known-bad: leftover debug hooks on a kernel path (JX007)
# tpusvm: kernel-path
import jax


@jax.jit
def inner_update(f, i):
    jax.debug.print("f[{}] = {}", i, f[i])  # JX007: host callback
    breakpoint()  # JX007: hangs non-interactive runs
    return f.at[i].add(1.0)
