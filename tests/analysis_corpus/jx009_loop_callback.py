# known-bad: host callbacks inside a lax loop body (JX009) — per-iteration
# device->host round trips; telemetry must be carry-resident instead
import jax
from jax import lax
from jax.experimental import io_callback


def solve(state0):
    def body(state):
        x, i = state
        jax.debug.print("gap[{}] = {}", i, x[0])  # JX009: callback per iter
        io_callback(lambda v: None, None, x)  # JX009: host escape per iter
        return (x * 0.5, i + 1)

    return lax.while_loop(lambda s: s[1] < 10, body, state0)
