# known-bad: Python control flow on traced values (JX001)
import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def relu_or_neg(x):
    if x.sum() > 0:  # JX001: tracer-dependent `if`
        return x
    while x[0] > 0:  # JX001: tracer-dependent `while`
        x = x - 1.0
    return -x


def countdown(x0):
    def body(x):
        if jnp.any(x > 0):  # JX001: tracer branch inside while_loop body
            x = x - 1
        return x

    return lax.while_loop(lambda x: x[0] > 0, body, x0)
