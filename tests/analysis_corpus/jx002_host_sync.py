# known-bad: implicit host-device synchronisation (JX002)
import jax
import numpy as np


@jax.jit
def step(x):
    lr = float(x[0])  # JX002: float() concretises a tracer
    host = np.asarray(x)  # JX002: host materialisation under jit
    return x * lr + host.sum()


def poll(batches):
    total = 0.0
    for b in batches:
        total += b.sum().item()  # JX002: per-iteration device sync
        b.block_until_ready()  # JX002: per-iteration device sync
    return total
