"""Driver entry-point contract tests.

The round driver compile-checks `entry()` single-chip and executes
`dryrun_multichip(N)` on N virtual CPU devices. Pin both contracts in the
suite so a regression is caught by pytest rather than by the unattended
driver run. The dryrun spawns a fresh interpreter when backends are
already initialised (as they are under pytest), which exercises the same
self-provisioning path the driver hits.
"""

import os
import subprocess
import sys

import jax
import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def test_entry_compiles_and_runs():
    from __graft_entry__ import entry

    fn, args = entry()
    out = jax.jit(fn)(*args)
    scores = np.asarray(out)
    assert scores.shape == (args[0].shape[0],)
    assert np.isfinite(scores).all()


def test_dryrun_multichip_direct_provisioning():
    # fresh interpreter, backends untouched: the dryrun provisions the
    # virtual CPU mesh directly
    code = (
        f"import sys; sys.path.insert(0, {_REPO!r}); "
        "from __graft_entry__ import dryrun_multichip; "
        "dryrun_multichip(4)"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    # the full topology x solver 2x2 must appear in the driver artifact
    # (VERDICT r3 #5), each combination having run its multi-round
    # convergence loop
    assert "dryrun_multichip OK" in r.stdout
    for combo in ("tree+blocked", "tree+pair", "star+blocked", "star+pair"):
        assert f"{combo} OK" in r.stdout, r.stdout
    assert "rounds" in r.stdout


def test_dryrun_multichip_non_power_of_two_runs_star_only():
    # the tree topology requires P = 2^k (reference parity,
    # mpi_svm_main3.cpp power-of-two check); a 6-device mesh must still
    # produce a star artifact instead of failing
    code = (
        f"import sys; sys.path.insert(0, {_REPO!r}); "
        "from __graft_entry__ import dryrun_multichip; "
        "dryrun_multichip(6)"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "star+blocked OK" in r.stdout and "star+pair OK" in r.stdout
    assert "tree+" not in r.stdout


def test_dryrun_multichip_after_backend_init():
    # the driver runs entry() FIRST, so dryrun_multichip sees initialised
    # backends with too few devices and must take its fresh-interpreter
    # retry branch — initialise backends in the child (with XLA_FLAGS
    # stripped so only 1 CPU device exists) to force exactly that path
    code = (
        f"import sys; sys.path.insert(0, {_REPO!r}); "
        # select CPU via jax.config BEFORE backend init (the env var alone
        # does not beat the force-registered accelerator plugin), then
        # initialise backends so dryrun sees them already up
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "assert len(jax.devices()) < 4; "
        "from __graft_entry__ import dryrun_multichip; "
        "dryrun_multichip(4)"
    )
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dryrun_multichip OK" in r.stdout
