"""JAX on-device solver vs. NumPy oracle: bit-level trajectory parity.

This is the TPU-native version of the reference's cross-implementation parity
methodology (SURVEY.md §4): every implementation must agree on the SV index
set, b, and iteration count. With float64 enabled both solvers follow the
same trajectory (same masked-argmin tie-breaks), so the comparison is exact
on iteration count / SV set and tight on floats.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, blobs, partition, rings
from tpusvm.oracle import get_sv_indices, smo_train
from tpusvm.oracle import predict as oracle_predict
from tpusvm.solver import predict as jax_predict
from tpusvm.solver import smo_solve
from tpusvm.status import Status

CFG = SVMConfig(C=1.0, gamma=0.125)


def _data(gen, **kw):
    X, Y = gen(**kw)
    Xs = MinMaxScaler().fit_transform(X)
    return Xs, Y


def _solve_both(Xs, Y, cfg, **jkw):
    o = smo_train(Xs, Y, cfg)
    j = smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y),
        C=cfg.C, gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau,
        max_iter=cfg.max_iter, **jkw,
    )
    return o, j


@pytest.mark.parametrize(
    "gen,kw,cfg",
    [
        (blobs, dict(n=120, seed=0), CFG),
        (blobs, dict(n=151, d=5, seed=7), CFG),
        (rings, dict(n=200, seed=1), SVMConfig(C=10.0, gamma=10.0)),
    ],
)
def test_solution_parity(gen, kw, cfg):
    # The reference's own parity criterion is SOLUTION-level, not
    # trajectory-level: its serial and GPU builds report b = -5.9026206 vs
    # -5.9027319 (agreement < 0.003%, SURVEY.md §6) yet identical SV sets and
    # accuracy. ulp-level reduction-order differences (XLA vs NumPy) shift
    # near-tied working-set picks, so iteration counts may differ by a few;
    # the converged solution must still agree tightly.
    Xs, Y = _data(gen, **kw)
    o, j = _solve_both(Xs, Y, cfg)
    assert int(j.status) == int(o.status) == Status.CONVERGED
    # same order of magnitude of work (sanity against runaway divergence)
    assert abs(int(j.n_iter) - o.n_iter) <= max(5, o.n_iter // 4)
    np.testing.assert_allclose(np.asarray(j.b), o.b, rtol=0, atol=1e-4)
    # the tau=1e-5 stopping tolerance only pins alphas to ~1e-4; compare
    # loosely (the reference never compares alphas at all, only SV count/b)
    np.testing.assert_allclose(np.asarray(j.alpha), o.alpha, atol=1e-3)
    # identical SV index set — the reference's headline correctness criterion
    sv_o = get_sv_indices(o.alpha)
    sv_j = get_sv_indices(np.asarray(j.alpha))
    np.testing.assert_array_equal(sv_o, sv_j)


def test_padding_invariance():
    # padded rows (validity mask False) must not change the result at all
    Xs, Y = _data(blobs, n=100, seed=3)
    o, j = _solve_both(Xs, Y, CFG)
    pad = 28
    Xp = np.concatenate([Xs, np.zeros((pad, Xs.shape[1]))])
    Yp = np.concatenate([Y, np.zeros(pad, np.int32)])
    valid = np.concatenate([np.ones(100, bool), np.zeros(pad, bool)])
    jp = smo_solve(
        jnp.asarray(Xp), jnp.asarray(Yp), valid=jnp.asarray(valid),
        C=CFG.C, gamma=CFG.gamma, eps=CFG.eps, tau=CFG.tau,
        max_iter=CFG.max_iter,
    )
    assert int(jp.n_iter) == int(j.n_iter)
    np.testing.assert_allclose(np.asarray(jp.alpha)[:100], np.asarray(j.alpha),
                               atol=1e-12)
    assert (np.asarray(jp.alpha)[100:] == 0).all()
    np.testing.assert_allclose(float(jp.b), float(j.b), atol=1e-12)


def test_warm_start_parity():
    Xs, Y = _data(blobs, n=90, seed=9)
    o = smo_train(Xs, Y, CFG)
    # perturb: zero out half the alphas, warm start both solvers from it
    a0 = np.array(o.alpha)
    a0[::2] = 0.0
    o2 = smo_train(Xs, Y, CFG, alpha0=a0, warm_start=True)
    j2 = smo_solve(
        jnp.asarray(Xs), jnp.asarray(Y), alpha0=jnp.asarray(a0),
        C=CFG.C, gamma=CFG.gamma, eps=CFG.eps, tau=CFG.tau,
        max_iter=CFG.max_iter, warm_start=True,
    )
    assert int(j2.status) == int(o2.status)
    assert abs(int(j2.n_iter) - o2.n_iter) <= max(5, o2.n_iter // 4)
    np.testing.assert_allclose(np.asarray(j2.alpha), o2.alpha, atol=1e-3)
    np.testing.assert_array_equal(
        get_sv_indices(np.asarray(j2.alpha)), get_sv_indices(o2.alpha)
    )


def test_predict_parity():
    Xs, Y = _data(blobs, n=80, seed=11)
    Xt, Yt = _data(blobs, n=40, seed=12)
    o, j = _solve_both(Xs, Y, CFG)
    po = oracle_predict(Xt, Xs, Y, o.alpha, o.b, CFG.gamma)
    pj = jax_predict(
        jnp.asarray(Xt), jnp.asarray(Xs), jnp.asarray(Y), j.alpha, j.b,
        gamma=CFG.gamma,
    )
    np.testing.assert_array_equal(po, np.asarray(pj))


@pytest.mark.parametrize("seed", [21, 22, 23, 24])
def test_cross_engine_parity_random(seed):
    """Every engine combination agrees with the f64 oracle on randomized
    data: blocked/XLA (exact selection), blocked/pallas-interpret (wss=2,
    approx selection). One shape across seeds so jit compiles once.

    This is the breadth complement to the targeted cases above — the same
    solution-level criterion (SV set, b) over varied geometry, exercising
    the duplicate-pick dedup, shrinking, and approx-selection paths."""
    from tpusvm.solver.blocked import blocked_smo_solve

    cfg = SVMConfig(C=10.0, gamma=2.0)
    Xs, Y = _data(blobs, n=128, d=6, seed=seed)
    o = smo_train(Xs, Y, cfg)
    assert o.status == Status.CONVERGED
    sv_o = set(get_sv_indices(o.alpha))

    common = dict(C=cfg.C, gamma=cfg.gamma, eps=cfg.eps, tau=cfg.tau,
                  accum_dtype=jnp.float64)
    r_x = blocked_smo_solve(jnp.asarray(Xs, jnp.float32), jnp.asarray(Y),
                            q=64, max_inner=128, inner="xla",
                            selection="exact", **common)
    r_p = blocked_smo_solve(jnp.asarray(Xs, jnp.float32), jnp.asarray(Y),
                            q=128, max_inner=256, inner="pallas", wss=2,
                            selection="approx", **common)
    for r in (r_x, r_p):
        assert int(r.status) == Status.CONVERGED
        sv = set(get_sv_indices(np.asarray(r.alpha)))
        # f32 features vs the oracle's f64: tau-band boundary flips allowed
        assert len(sv ^ sv_o) <= max(2, len(sv_o) // 25)
        np.testing.assert_allclose(float(r.b), o.b, atol=2e-3)
