"""Round 9 solver speed ladder: shrinking, K-row cache, precision rungs,
fused selection.

Parity discipline: every rung must reproduce the never-shrunk/full-
precision solve at the SOLUTION level (the reference's own criterion —
identical SV set, b within the oracle-parity bands, stopping rule
satisfied), and the shrinking driver's final stopping decision must be
THE UNSHRUNK CRITERION — asserted here against an independent NumPy
reconstruction of f, not against the solver's own bookkeeping.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import RAW_BF16, resolve_matmul_precision
from tpusvm.data import MinMaxScaler, blobs, rings
from tpusvm.solver.blocked import blocked_smo_solve
from tpusvm.solver.shrink import shrinking_blocked_solve
from tpusvm.status import Status

f64 = jnp.float64


def _data(gen, **kw):
    X, Y = gen(**kw)
    return MinMaxScaler().fit_transform(X).astype(np.float32), Y


def _keerthi_gap(Xs, Y, alpha, gamma, C, eps=1e-12):
    """Independent f64 NumPy reconstruction of the full-problem Keerthi
    gap b_low - b_high — the unshrunk stopping quantity, computed with
    no solver machinery at all."""
    Xs = np.asarray(Xs, np.float64)
    a = np.asarray(alpha, np.float64)
    y = np.asarray(Y, np.float64)
    d2 = ((Xs ** 2).sum(1)[:, None] + (Xs ** 2).sum(1)[None, :]
          - 2.0 * Xs @ Xs.T)
    K = np.exp(-gamma * np.maximum(d2, 0.0))
    f = K @ (a * y) - y
    m_h = np.where(y == 1, a < C - eps, (y == -1) & (a > eps))
    m_l = np.where(y == 1, a > eps, (y == -1) & (a < C - eps))
    return float(f[m_l].max() - f[m_h].min())


def _gap_band(alpha, tau=1e-5):
    """2*tau plus the f32-kernel-evaluation noise floor: the solver
    judges the criterion on f built from f32 kernel values (~1e-7
    relative), so an f64 re-evaluation of the same alphas can sit
    ~sum(alpha)*1e-7 past the band (the documented refine-mode floor,
    solver/blocked.py). Scale-aware, like the fuzz harness's b bands."""
    return 2.0 * tau + 4e-7 * float(np.sum(np.asarray(alpha)))


def _svs(alpha, tol=1e-8):
    return set(np.flatnonzero(np.asarray(alpha) > tol).tolist())


KW = dict(C=10.0, gamma=10.0, tau=1e-5, q=64, max_inner=256,
          accum_dtype=f64, max_outer=20000, max_iter=10_000_000)


# ------------------------------------------------------------- shrinking
def test_shrink_matches_unshrunk_and_final_criterion_is_global():
    Xs, Y = _data(rings, n=768, seed=5)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    r0 = blocked_smo_solve(Xj, Yj, **KW)
    r1, hist = shrinking_blocked_solve(
        Xj, Yj, shrink_every=4, shrink_stable=2, shrink_min=64,
        return_history=True, **KW)
    assert int(r0.status) == Status.CONVERGED
    assert int(r1.status) == Status.CONVERGED
    assert _svs(r0.alpha) == _svs(r1.alpha)
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-3)
    # the final stopping decision is the UNSHRUNK criterion: both
    # solutions satisfy it on an independent full-f reconstruction,
    # judged by the SAME band (criterion identity)
    for r in (r0, r1):
        assert _keerthi_gap(Xs, Y, r.alpha, 10.0, 10.0) \
            <= _gap_band(r.alpha)


def test_shrink_adversarial_wrong_freeze_is_revived():
    """Force WRONG freezing (S=1, shrink at every pause, gap guard off):
    rows freeze off a single round's look at a still-loose band, so the
    compacted optimum diverges from the global one. The un-shrink pass
    must REJECT each compacted convergence claim, revive the wrongly
    frozen alphas and keep optimising until the GLOBAL criterion holds —
    observable as repeated un-shrink events with the round counter
    advancing past them, and a final solution identical to never-shrunk."""
    from benchmarks.common import make_workload

    Xs, Y = make_workload(512, d=32)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    kw = dict(C=10.0, gamma=0.00125 * 784 / 32, tau=1e-5, q=64,
              max_inner=256, accum_dtype=f64, max_outer=20000,
              max_iter=10_000_000)
    r0 = blocked_smo_solve(Xj, Yj, **kw)
    r1, hist = shrinking_blocked_solve(
        Xj, Yj, shrink_every=1, shrink_stable=1, shrink_min=64,
        shrink_gap_factor=0.0, max_unshrinks=6,
        return_history=True, **kw)
    assert int(r0.status) == Status.CONVERGED
    assert int(r1.status) == Status.CONVERGED
    unshrunk_rounds = [h["round"] for h in hist
                       if h["event"] == "unshrink"]
    # at least one compacted claim was rejected (a second un-shrink ran)
    # and optimisation continued past the first revival
    assert len(unshrunk_rounds) >= 2
    assert int(r1.n_outer) > unshrunk_rounds[0]
    # ...to the never-shrunk solution, under the unshrunk criterion
    assert _svs(r0.alpha) == _svs(r1.alpha)
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-3)
    gamma = 0.00125 * 784 / 32
    assert _keerthi_gap(Xs, Y, r1.alpha, gamma, 10.0) \
        <= _gap_band(r1.alpha)


def test_shrink_fuzz_corpus_parity():
    """Fuzz-corpus gate: on random instances the shrunk solve must keep
    the never-shrunk solve's SV set exactly and satisfy the identical
    stopping criterion (independent reconstruction)."""
    from benchmarks.common import random_instance

    for seed in (101, 202, 303, 404):
        rng = np.random.default_rng(seed)
        _, n, X, Y, C, gamma = random_instance(
            rng, seed, (128, 512), (2, 12), [1.0, 10.0], [0.5, 2.0, 8.0])
        Xs = MinMaxScaler().fit_transform(X).astype(np.float32)
        kw = dict(C=C, gamma=gamma, tau=1e-5, q=64, max_inner=256,
                  accum_dtype=f64, max_outer=20000, max_iter=10_000_000)
        r0 = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y), **kw)
        r1 = shrinking_blocked_solve(
            jnp.asarray(Xs), jnp.asarray(Y), shrink_every=4,
            shrink_stable=2, shrink_min=64, **kw)
        assert int(r0.status) == Status.CONVERGED, seed
        assert int(r1.status) == Status.CONVERGED, seed
        assert _svs(r0.alpha) == _svs(r1.alpha), seed
        np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-3)
        gap = _keerthi_gap(Xs, Y, r1.alpha, gamma, C)
        assert gap <= _gap_band(r1.alpha), (seed, gap)


def test_shrink_driver_validation():
    X = jnp.zeros((16, 2), jnp.float32)
    Y = jnp.asarray([1, -1] * 8, jnp.int32)
    with pytest.raises(ValueError, match="shrink_stable"):
        shrinking_blocked_solve(X, Y, shrink_stable=0)
    with pytest.raises(ValueError, match="shrink_every"):
        shrinking_blocked_solve(X, Y, shrink_every=0)
    with pytest.raises(ValueError, match="segmenting"):
        shrinking_blocked_solve(X, Y, pause_at=3)
    with pytest.raises(ValueError, match="bf16_f32"):
        shrinking_blocked_solve(X, Y, matmul_precision="default")


def test_shrink_telemetry_ring_carries_active_set():
    Xs, Y = _data(rings, n=512, seed=5)
    r, hist = shrinking_blocked_solve(
        jnp.asarray(Xs), jnp.asarray(Y), shrink_every=4, shrink_stable=2,
        shrink_min=64, telemetry=4096, return_history=True, **KW)
    from tpusvm.obs.convergence import materialize

    conv = materialize(r.telemetry)
    # the ring crossed driver segments/compactions intact: every body
    # execution of the whole solve is recorded (proceed rounds plus the
    # terminal checks each segment/un-shrink runs), and the active
    # column dips when a compaction was in force
    assert conv["rounds_recorded"] > int(r.n_outer)
    assert "active" in conv
    if any(h["event"] == "shrink" for h in hist):
        assert conv["active"].min() < 512
    assert conv["active"].max() == 512


# ----------------------------------------------------------- K-row cache
def test_krow_cache_same_solution_and_accounting():
    Xs, Y = _data(rings, n=512, seed=5)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    kw = dict(KW, q=32, max_inner=64)
    r0 = blocked_smo_solve(Xj, Yj, **kw)
    r1 = blocked_smo_solve(Xj, Yj, krow_cache=512, **kw)
    assert int(r1.status) == Status.CONVERGED
    assert _svs(r0.alpha) == _svs(r1.alpha)
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-4)
    # accounting: every proceed-round classified as hit or miss, in rows
    assert (int(r1.cache_hits) + int(r1.cache_misses)
            == 32 * int(r1.n_outer))
    # the repeat-violator regime near convergence actually hits
    assert int(r1.cache_hits) > 0


def test_krow_cache_slot_aliasing_evicted_row_recomputed():
    """Slot-aliasing gate: with the cache squeezed to exactly q slots,
    EVERY miss round evicts the whole previous working set. A stale-key
    bug (lookup matching a slot whose row was evicted) would serve wrong
    K-rows and derail the solve; the solution must stay identical to the
    pressure-free cache and to no cache at all."""
    Xs, Y = _data(rings, n=384, seed=7)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    kw = dict(KW, q=32, max_inner=64)
    r_no = blocked_smo_solve(Xj, Yj, **kw)
    r_tight = blocked_smo_solve(Xj, Yj, krow_cache=32, **kw)   # q slots
    r_roomy = blocked_smo_solve(Xj, Yj, krow_cache=384, **kw)
    assert int(r_tight.status) == Status.CONVERGED
    # tight vs roomy: same rows-form trajectory wherever lookups are
    # correct — any stale hit would split them
    np.testing.assert_array_equal(np.asarray(r_tight.alpha),
                                  np.asarray(r_roomy.alpha))
    assert float(r_tight.b) == float(r_roomy.b)
    assert _svs(r_no.alpha) == _svs(r_tight.alpha)
    np.testing.assert_allclose(float(r_tight.b), float(r_no.b), atol=1e-4)


def test_krow_cache_validation():
    X = jnp.zeros((64, 2), jnp.float32)
    Y = jnp.asarray([1, -1] * 32, jnp.int32)
    with pytest.raises(ValueError, match="krow_cache"):
        blocked_smo_solve(X, Y, q=32, krow_cache=16)  # slots < q
    with pytest.raises(ValueError, match="krow_cache"):
        blocked_smo_solve(X, Y, q=32, krow_cache=64, fused_fupdate=True)


# ------------------------------------------------------- precision ladder
def test_matmul_precision_resolver_closes_the_default_footgun():
    from tpusvm.ops.rbf import matmul_p, rbf_cross_matvec

    with pytest.raises(ValueError, match="RAW SINGLE-PASS bf16"):
        resolve_matmul_precision("default")
    assert resolve_matmul_precision(None) == "float32"
    assert resolve_matmul_precision(RAW_BF16) == RAW_BF16
    with pytest.raises(ValueError, match="unknown matmul precision"):
        resolve_matmul_precision("bf16")
    # the ops layer inherits the gate: the old silent spelling now raises
    A = jnp.ones((8, 4), jnp.float32)
    with pytest.raises(ValueError, match="RAW SINGLE-PASS bf16"):
        rbf_cross_matvec(A, A[:2], jnp.ones(2, jnp.float32), 0.5,
                         precision="default")
    # the ladder rungs compute: rounded operands, f32 accumulate,
    # compensation strictly reduces the left operand's rounding error
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.random((128, 32)), jnp.float32)
    B = jnp.asarray(rng.random((32, 16)), jnp.float32)
    exact = np.asarray(matmul_p(A, B, "highest"), np.float64)
    e1 = np.abs(np.asarray(matmul_p(A, B, "bf16_f32"), np.float64)
                - exact).max()
    e2 = np.abs(np.asarray(matmul_p(A, B, "bf16_f32c"), np.float64)
                - exact).max()
    assert 0 < e2 < e1


def test_bf16_f32_requires_drift_guard_and_matches_baseline():
    Xs, Y = _data(rings, n=512, seed=5)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    with pytest.raises(ValueError, match="bf16_f32"):
        blocked_smo_solve(Xj, Yj, matmul_precision="bf16_f32", **KW)
    r0 = blocked_smo_solve(Xj, Yj, **KW)
    # rung A: refine-guarded (the matmul_precision='default' discipline)
    r1 = blocked_smo_solve(Xj, Yj, matmul_precision="bf16_f32",
                           refine=512, max_refines=2, **KW)
    # rung B: shrink-guarded (the un-shrink rebuild is the revalidation)
    r2 = shrinking_blocked_solve(
        Xj, Yj, shrink_every=4, shrink_stable=2, shrink_min=64,
        matmul_precision="bf16_f32", **KW)
    for r in (r1, r2):
        assert int(r.status) == Status.CONVERGED
        sv0, sv = _svs(r0.alpha), _svs(r.alpha)
        # bf16-rounded operands genuinely change the arithmetic (unlike
        # the CPU no-op 'default' hint), so allow tau-band boundary flips
        assert len(sv0 ^ sv) <= max(2, len(sv0) // 10)
        np.testing.assert_allclose(float(r.b), float(r0.b), atol=5e-3)
    # the shrink-guarded run's final claim was re-validated globally
    assert _keerthi_gap(Xs, Y, r2.alpha, 10.0, 10.0) <= 2e-5 * (1 + 1e-6)


def test_bf16_rungs_resolve_fused_off():
    from tpusvm.solver.blocked import resolve_fused_fupdate

    assert resolve_fused_fupdate(60000, 784, q=2048,
                                 matmul_precision="bf16_f32") is False
    with pytest.raises(ValueError, match="full-f32"):
        resolve_fused_fupdate(60000, 784, q=2048, fused=True,
                              matmul_precision="bf16_f32")


# -------------------------------------------------------- fused selection
def test_fused_selection_same_optimum_interpret():
    Xs, Y = _data(rings, n=200, seed=5)
    Xj, Yj = jnp.asarray(Xs), jnp.asarray(Y)
    kw = dict(C=10.0, gamma=10.0, tau=1e-5, q=32, max_inner=64,
              accum_dtype=f64)
    r0 = blocked_smo_solve(Xj, Yj, **kw)
    r1 = blocked_smo_solve(Xj, Yj, fused_fupdate=True,
                           pallas_fused_selection=True, **kw)
    assert int(r1.status) == Status.CONVERGED
    assert _svs(r0.alpha) == _svs(r1.alpha)
    np.testing.assert_allclose(float(r1.b), float(r0.b), atol=1e-3)
    assert float(r1.b_low) <= float(r1.b_high) + 2e-5 * (1 + 1e-6)


def test_fused_selection_flag_validation():
    X = jnp.zeros((64, 2), jnp.float32)
    Y = jnp.asarray([1, -1] * 32, jnp.int32)
    # active flag with the fused f-update resolved OFF = config lie
    with pytest.raises(ValueError, match="pallas_fused_selection"):
        blocked_smo_solve(X, Y, q=32, pallas_fused_selection=True)
    with pytest.raises(ValueError, match="refine"):
        blocked_smo_solve(X, Y, q=32, fused_fupdate=True,
                          pallas_fused_selection=True, refine=64)
    with pytest.raises(ValueError, match="selection"):
        blocked_smo_solve(X, Y, q=32, fused_fupdate=True,
                          pallas_fused_selection=True, selection="exact")


def test_selection_shape_invariants():
    from tpusvm.ops.pallas.fused_fupdate import selection_shape

    for n, d, q in ((240, 2, 64), (60000, 784, 2048), (512, 16, 128)):
        block, nb, k_cand, ncand = selection_shape(n, d, q)
        assert nb == -(-n // block)
        assert ncand == nb * k_cand
        assert ncand >= q // 2          # a full half fits the pool
        assert k_cand <= block
        assert ncand <= n or k_cand == 8  # tiny-n floor may overshoot


# ------------------------------------------------- persistence/provenance
def test_model_provenance_roundtrip(tmp_path):
    from tpusvm.config import SVMConfig
    from tpusvm.models import BinarySVC

    Xs, Y = _data(rings, n=240, seed=3)
    # max_iter keeps the fit cheap: provenance recording, not
    # convergence, is under test (bf16 on this tiny ring can wander)
    m = BinarySVC(config=SVMConfig(C=10.0, gamma=10.0, max_iter=2000),
                  solver_opts={"q": 32, "shrink_every": 4,
                               "shrink_min": 64,
                               "matmul_precision": "bf16_f32"})
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("ignore", RuntimeWarning)
        m.fit(Xs, Y)
    assert m.train_precision_ == "bf16_f32"
    assert m.shrink_every_ == 4
    path = str(tmp_path / "prov.npz")
    m.save(path)
    m2 = BinarySVC.load(path)
    assert m2.train_precision_ == "bf16_f32"
    assert m2.shrink_every_ == 4 and m2.shrink_stable_ == 3
    # pre-v3 state (no provenance fields) loads with the defaults
    from tpusvm.models.serialization import load_model, save_model

    state, cfg = load_model(path)
    for k in ("train_precision", "shrink_every", "shrink_stable"):
        state.pop(k)
    old = str(tmp_path / "old.npz")
    save_model(old, state, cfg)
    m3 = BinarySVC.load(old)
    assert m3.train_precision_ == "f32"
    assert m3.shrink_every_ == 0
    np.testing.assert_array_equal(m3.sv_alpha_, m2.sv_alpha_)


def test_checkpoint_fingerprint_pins_ladder_statics(tmp_path):
    from tpusvm.solver.checkpoint import (
        load_solver_state,
        save_solver_state,
        solve_fingerprint,
    )

    Xs, Y = _data(blobs, n=64, seed=1)
    kw = dict(C=1.0, gamma=0.5, q=16)
    r, st = blocked_smo_solve(jnp.asarray(Xs), jnp.asarray(Y),
                              return_state=True, **kw)
    fp = solve_fingerprint(Xs, Y, None, dict(kw, krow_cache=16))
    path = str(tmp_path / "ck.npz")
    st_np = type(st)(*(np.asarray(x) for x in st))
    save_solver_state(path, st_np, fp)
    load_solver_state(path, fp)  # roundtrips
    with pytest.raises(ValueError, match="krow_cache"):
        load_solver_state(path, solve_fingerprint(
            Xs, Y, None, dict(kw, krow_cache=32)))
