"""REAL multi-process distributed cascade — executed, not monkeypatched.

The reference genuinely ran multi-node: MPI_Init (mpi_svm_main3.cpp:416-419)
launched via SLURM on 2 nodes x 32 tasks (code/mpi_svm3.sh). Round 2 wired
`jax.distributed.initialize` behind the CLI's --distributed flag but only
covered it by monkeypatching initialize away (VERDICT r2, missing #3).
These tests launch an actual 2-process CPU "cluster" on localhost: both
processes join one coordinator, form a single GLOBAL 2-device mesh (one CPU
device per process — XLA_FLAGS is stripped so the device/process mapping is
1:1), and run the full cascade convergence loop whose collectives
(lax.ppermute tree exchange, lax.all_gather star merge and the
round-result broadcast) genuinely cross the process boundary over the
distributed runtime, exercising the same code path a multi-host TPU pod
uses over DCN.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_cluster(extra_args, num_processes=2, timeout=420,
                 per_process_args=None, devices_per_process=1):
    """Launch the CLI on every 'host' of the localhost cluster; returns
    [(rc, output), ...] in process-id order."""
    port = _free_port()
    # default one CPU device per process: the global mesh then spans
    # processes, which is the whole point (8 virtual devices per process
    # would let a 2-shard mesh land entirely on process 0).
    # devices_per_process > 1 models a real pod host (several chips per
    # host): collectives must cross BOTH device and process boundaries.
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    if devices_per_process > 1:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices_per_process}"
        )
    procs = []
    outfiles = []
    for pid in range(num_processes):
        # per-process temp FILES, not PIPEs: output is drained sequentially
        # after wait, and an undrained 64KB pipe could block a chatty rank
        # mid-collective and deadlock the whole cluster into the timeout
        f = tempfile.TemporaryFile(mode="w+", encoding="utf-8")
        outfiles.append(f)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "tpusvm",
             "--platform", "cpu",
             "--distributed",
             "--coordinator-address", f"127.0.0.1:{port}",
             "--num-processes", str(num_processes),
             "--process-id", str(pid),
             *extra_args,
             *(per_process_args[pid] if per_process_args else [])],
            cwd=_REPO, env=env, text=True,
            stdout=f, stderr=subprocess.STDOUT,
        ))
    results = []
    try:
        for p, f in zip(procs, outfiles):
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            f.seek(0)
            results.append((p.returncode, f.read()))
    finally:
        for f in outfiles:
            f.close()
    return results


@pytest.mark.parametrize("topology", ["tree", "star"])
def test_two_process_cascade_converges(topology, tmp_path):
    import numpy as np

    jsonl = tmp_path / "run.jsonl"
    models = [tmp_path / f"model{pid}.npz" for pid in (0, 1)]
    results = _run_cluster(
        [
            "train", "--synthetic", "blobs", "--n", "64", "--n-test", "32",
            "--d", "8", "--gamma", "0.5", "--C", "1.0",
            "--mode", "cascade", "--topology", topology,
            "--shards", "2", "--sv-capacity", "32", "--max-rounds", "5",
            "--jsonl", str(jsonl),
        ],
        per_process_args=[["--save", str(m)] for m in models],
    )
    for rc, out in results:
        assert rc == 0, out[-3000:]
    out0 = results[0][1]
    # the reference's per-round diagnostics contract, printed by rank 0
    # (RunLogger output is process-0-only, like the reference's
    # if(rank==0) printing)
    assert "=== Round" in out0
    assert "converged = True" in out0
    assert "SV count" in out0
    # every process ran the SAME global computation in SPMD lockstep and
    # holds the same replicated model: compare what each process saved
    with np.load(models[0]) as m0, np.load(models[1]) as m1:
        np.testing.assert_array_equal(m0["sv_ids"], m1["sv_ids"])
        np.testing.assert_array_equal(m0["sv_alpha"], m1["sv_alpha"])
        assert float(m0["b"]) == float(m1["b"])
        assert len(m0["sv_ids"]) > 0
    # structured log written by process 0 records a converged cascade
    events = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    train_evts = [e for e in events if e.get("event") == "train"]
    assert train_evts and train_evts[0]["status"] == "CONVERGED"
    assert train_evts[0]["sv_count"] > 0


def test_two_process_checkpoint_then_resume(tmp_path):
    """Checkpointing under the 2-process cluster: only process 0 writes
    the round state (rank-0 IO), and a second cluster launch resumes from
    it — the cascade's inter-round state survives a full cluster restart,
    the elastic-recovery property the reference lacks entirely."""
    ckpt = tmp_path / "cascade.npz"
    base = [
        "train", "--synthetic", "blobs", "--n", "64", "--n-test", "0",
        "--d", "8", "--gamma", "0.5", "--C", "1.0",
        "--mode", "cascade", "--topology", "star",
        "--shards", "2", "--sv-capacity", "32",
        "--checkpoint", str(ckpt),
    ]
    # run 1: stop after a single round (max_rounds=1 cannot converge —
    # convergence needs two rounds with equal ID sets)
    results = _run_cluster(base + ["--max-rounds", "1"])
    for rc, out in results:
        assert rc == 0, out[-3000:]
    assert ckpt.exists()
    # run 2: a fresh cluster resumes from the checkpoint and converges
    results = _run_cluster(base + ["--max-rounds", "6", "--resume"])
    for rc, out in results:
        assert rc == 0, out[-3000:]
    out0 = results[0][1]
    assert "resuming cascade from round 2" in out0
    assert "converged = True" in out0


def test_two_process_divergent_resume_fails_fast(tmp_path):
    """ADVICE r3 medium, end-to-end: resume=True where the checkpoint file
    exists on process 0 but is MISSING on process 1 (no shared
    filesystem) must fail fast on EVERY process with the explained
    shared-filesystem error — not deadlock in the first round collective
    with one process at round 2 and the other at round 1."""
    ckpt0 = tmp_path / "ck0.npz"
    ckpt1 = tmp_path / "ck1.npz"  # never written: the 'other host' path
    base = [
        "train", "--synthetic", "blobs", "--n", "64", "--n-test", "0",
        "--d", "8", "--gamma", "0.5", "--C", "1.0",
        "--mode", "cascade", "--topology", "star",
        "--shards", "2", "--sv-capacity", "32",
    ]
    results = _run_cluster(
        base + ["--max-rounds", "1", "--checkpoint", str(ckpt0)])
    for rc, out in results:
        assert rc == 0, out[-3000:]
    assert ckpt0.exists() and not ckpt1.exists()
    results = _run_cluster(
        base + ["--max-rounds", "6", "--resume"],
        per_process_args=[["--checkpoint", str(ckpt0)],
                          ["--checkpoint", str(ckpt1)]],
        timeout=240,  # must fail FAST; a deadlock would ride to timeout
    )
    for rc, out in results:
        assert rc != 0, out[-3000:]
        assert "missing on processes [1]" in out, out[-3000:]


@pytest.mark.parametrize("topology", ["tree", "star"])
def test_two_process_four_device_mesh(topology, tmp_path):
    """The real pod shape — multiple devices PER process (2 hosts x 2
    'chips'): a 4-shard cascade whose merge collectives cross both the
    intra-process device boundary and the inter-process one in a single
    mesh axis (tree's ppermute exchange and star's all_gather both run
    mixed intra/inter-process). This is the topology a multi-host TPU
    slice presents (ICI within a host's chips, DCN between hosts)."""
    import numpy as np

    models = [tmp_path / f"model{pid}.npz" for pid in (0, 1)]
    results = _run_cluster(
        [
            "train", "--synthetic", "blobs", "--n", "128", "--n-test", "0",
            "--d", "8", "--gamma", "0.5", "--C", "1.0",
            "--mode", "cascade", "--topology", topology,
            "--shards", "4", "--sv-capacity", "64", "--max-rounds", "5",
        ],
        per_process_args=[["--save", str(m)] for m in models],
        devices_per_process=2,
    )
    for rc, out in results:
        assert rc == 0, out[-3000:]
    assert "converged = True" in results[0][1]
    with np.load(models[0]) as m0, np.load(models[1]) as m1:
        np.testing.assert_array_equal(m0["sv_ids"], m1["sv_ids"])
        assert len(m0["sv_ids"]) > 0


def test_two_process_class_parallel_multiclass(tmp_path):
    """Class-parallel OVR across PROCESS boundaries (round 4): the class
    axis sharded over a global 2-device / 2-process mesh — each process
    trains half the one-vs-rest problems, the end-of-solve all_gather
    crosses the process boundary, and every process holds (and saves) the
    full replicated model. BASELINE config 5 at the reference's
    multi-node granularity."""
    import numpy as np

    models = [tmp_path / f"model{pid}.npz" for pid in (0, 1)]
    results = _run_cluster(
        [
            "train", "--synthetic", "mnist-like", "--multiclass",
            "--class-parallel", "--n", "192", "--n-test", "64",
            "--d", "16", "--gamma", "0.0625",
        ],
        per_process_args=[["--save", str(m)] for m in models],
    )
    for rc, out in results:
        assert rc == 0, out[-3000:]
    assert "classes = " in results[0][1]
    with np.load(models[0]) as m0, np.load(models[1]) as m1:
        assert len(m0["classes"]) == 10
        np.testing.assert_array_equal(m0["classes"], m1["classes"])
        np.testing.assert_array_equal(m0["coef"], m1["coef"])
        np.testing.assert_array_equal(m0["b"], m1["b"])
        np.testing.assert_array_equal(m0["sv_X"], m1["sv_X"])
        assert m0["coef"].shape[0] == 10 and m0["sv_X"].shape[0] > 0


def test_two_process_mesh_spans_processes():
    """The info command must see one global 2-device mesh (process_count 2,
    one addressable device each) — proof the cluster actually formed, not
    two standalone runs."""
    results = _run_cluster(["info"], timeout=180)
    for pid, (rc, out) in enumerate(results):
        assert rc == 0, out[-3000:]
        assert f"process {pid}/2" in out
