"""Serving-SLO tests (ServeConfig slo_*, serve/metrics SLOConfig).

Contracts (the acceptance bar):
  * a forced-latency fault plan (tpusvm.faults latency injection on
    serve.score) flips /healthz to "degraded" within one window;
  * burn-rate gauges appear on /metrics (text) and in the snapshot;
  * the window actually slides (injectable clock): violations age out
    and the burn returns to zero;
  * error burn counts served-and-failed outcomes, not admission-control
    rejections;
  * slo_shed feeds the burn into the admission path (OVERLOADED);
  * with no SLO configured nothing changes (no gauges, health "ok").
"""

import urllib.request

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from tpusvm import faults  # noqa: E402
from tpusvm.config import SVMConfig  # noqa: E402
from tpusvm.data import rings  # noqa: E402
from tpusvm.models import BinarySVC  # noqa: E402
from tpusvm.serve import ServeConfig, Server  # noqa: E402
from tpusvm.serve.metrics import Metrics, SLOConfig  # noqa: E402


@pytest.fixture(scope="module")
def model():
    X, Y = rings(n=240, seed=3)
    return BinarySVC(SVMConfig(C=10.0, gamma=10.0), dtype=jnp.float64).fit(
        X, Y), X


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


# ------------------------------------------------------------ metrics unit
def test_slo_window_slides_with_clock():
    clock = FakeClock()
    m = Metrics(buckets=(1, 2), slo=SLOConfig(p99_ms=5.0, window_s=10.0),
                clock=clock)
    for _ in range(4):
        m.observe_latency(0.050)  # 50 ms >> the 5 ms target
    m.inc("ok", 4)
    st = m.slo_status()
    assert st["burning"] and st["latency_burn"] == pytest.approx(100.0)
    # one window later the violations have aged out
    clock.t += 11.0
    st = m.slo_status()
    assert st["latency_burn"] == 0.0 and not st["burning"]
    assert st["window_requests"] == 0


def test_error_burn_counts_failures_not_shedding():
    clock = FakeClock()
    m = Metrics(buckets=(1,), slo=SLOConfig(p99_ms=1000.0,
                                            error_budget=0.1,
                                            window_s=60.0), clock=clock)
    m.inc("ok", 9)
    m.inc("errors", 1)        # 10% error rate / 10% budget = burn 1.0
    m.inc("overloaded", 50)   # shedding must NOT burn the error budget
    m.inc("queue_full", 50)
    st = m.slo_status()
    assert st["error_burn"] == pytest.approx(1.0)
    assert st["burning"]
    assert st["window_requests"] == 10


def test_slo_validation():
    with pytest.raises(ValueError):
        SLOConfig(p99_ms=0.0).validate()
    with pytest.raises(ValueError):
        SLOConfig(p99_ms=1.0, error_budget=1.5).validate()
    with pytest.raises(ValueError):
        ServeConfig(slo_shed=True).resolved_slo()  # shed needs a target
    assert ServeConfig().resolved_slo() is None
    assert ServeConfig(slo_p99_ms=5.0).resolved_slo().p99_ms == 5.0


def test_no_slo_means_no_gauges_and_unchanged_snapshot():
    m = Metrics(buckets=(1,))
    m.inc("ok", 3)
    m.observe_latency(0.5)
    snap = m.snapshot()
    assert "slo" not in snap
    assert m.slo_status() is None
    assert "slo" not in m.render_text()


# --------------------------------------------------- the acceptance test
def test_latency_fault_plan_degrades_healthz_within_one_window(model):
    svc, X = model
    plan = faults.FaultPlan.from_json({
        "format_version": 1, "seed": 7,
        "rules": [{"point": "serve.score", "kind": "latency",
                   "p": 1.0, "delay_ms": 40.0}],
    })
    cfg = ServeConfig(max_batch=4, slo_p99_ms=5.0, slo_window_s=60.0)
    with Server(cfg, dtype=jnp.float64) as srv:
        srv.add_model("m", svc)
        srv.warmup()
        assert srv.health()["status"] == "ok"
        with faults.active(plan):
            for i in range(6):
                r = srv.submit("m", X[i])
                assert r.ok, r.status
        h = srv.health()
        assert h["status"] == "degraded"
        assert h["slo"]["m"]["burning"] is True
        assert h["slo"]["m"]["latency_burn"] >= 1.0
        # burn gauges are on the text /metrics surface
        text = srv.metrics_text()
        assert 'tpusvm_serve_slo_latency_burn{model="m"}' in text
        assert 'tpusvm_serve_slo_burning{model="m"} 1' in text
        # and in the JSON snapshot + mergeable registry view
        snap = srv.metrics("m")
        assert snap["slo"]["burning"] is True
        reg = srv._worker("m").metrics.registry_snapshot()
        names = {e["name"] for e in reg["metrics"]}
        assert "serve.slo_latency_burn" in names


def test_http_healthz_and_metrics_carry_slo(model):
    import json as _json

    from tpusvm.serve.http import make_http_server, start_http_thread

    svc, X = model
    plan = faults.FaultPlan.from_json({
        "format_version": 1, "seed": 7,
        "rules": [{"point": "serve.score", "kind": "latency",
                   "p": 1.0, "delay_ms": 40.0}],
    })
    cfg = ServeConfig(max_batch=4, slo_p99_ms=5.0, slo_window_s=60.0)
    with Server(cfg, dtype=jnp.float64) as srv:
        srv.add_model("m", svc)
        srv.warmup()
        httpd = make_http_server(srv, port=0)
        start_http_thread(httpd)
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with faults.active(plan):
                body = _json.dumps(
                    {"instances": X[:4].tolist()}).encode()
                req = urllib.request.Request(
                    f"{base}/v1/models/m:predict", data=body,
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req).read()
            h = _json.loads(
                urllib.request.urlopen(f"{base}/healthz").read())
            # degraded is still HTTP 200 (other models may be fine)
            assert h["status"] == "degraded"
            assert h["slo"]["m"]["burning"] is True
            metrics = urllib.request.urlopen(
                f"{base}/metrics").read().decode()
            assert "tpusvm_serve_slo_latency_burn" in metrics
        finally:
            httpd.shutdown()


def test_slo_shed_feeds_admission_control(model):
    from tpusvm.status import ServeStatus

    svc, X = model
    plan = faults.FaultPlan.from_json({
        "format_version": 1, "seed": 7,
        "rules": [{"point": "serve.score", "kind": "latency",
                   "p": 1.0, "delay_ms": 40.0, "max_hits": 6}],
    })
    cfg = ServeConfig(max_batch=4, slo_p99_ms=5.0, slo_window_s=60.0,
                      slo_shed=True)
    with Server(cfg, dtype=jnp.float64) as srv:
        srv.add_model("m", svc)
        srv.warmup()
        with faults.active(plan):
            for i in range(6):
                r = srv.submit("m", X[i])
                if not r.ok:
                    break
        # the budget is burning: new work is shed with OVERLOADED before
        # it queues
        r = srv.submit("m", X[0])
        assert ServeStatus(r.status) == ServeStatus.OVERLOADED
        assert srv.metrics("m")["overloaded"] >= 1


def test_healthy_traffic_stays_ok(model):
    svc, X = model
    cfg = ServeConfig(max_batch=4, slo_p99_ms=2000.0, slo_window_s=60.0)
    with Server(cfg, dtype=jnp.float64) as srv:
        srv.add_model("m", svc)
        srv.warmup()
        for i in range(8):
            assert srv.submit("m", X[i]).ok
        h = srv.health()
        assert h["status"] == "ok"
        assert h["slo"]["m"]["burning"] is False
        scores_direct, _ = srv.predict_direct("m", X[:8])
        ref = np.asarray(svc.decision_function(X[:8]))
        np.testing.assert_array_equal(np.asarray(scores_direct), ref)
