"""Data layer tests: CSV semantics, scaler semantics, partitioner."""

import numpy as np
import pytest

from tpusvm.data import (
    MinMaxScaler,
    blobs,
    mnist_like,
    partition,
    read_csv,
    write_csv,
)


def test_csv_roundtrip(tmp_path):
    X = np.array([[0.5, 1.5], [2.0, -3.0], [4.25, 0.0]])
    Y = np.array([1, -1, 1], np.int32)
    p = tmp_path / "d.csv"
    write_csv(str(p), X, Y)
    X2, Y2 = read_csv(str(p))
    np.testing.assert_array_equal(X, X2)
    np.testing.assert_array_equal(Y, Y2)


def test_csv_label_mapping_and_short_rows(tmp_path):
    # label != 1 -> -1 (main3.cpp:49-52); rows with < 2 fields skipped (:41)
    p = tmp_path / "d.csv"
    p.write_text("a,b,label\n1.0,2.0,7\n\n3.0,4.0,1\n9\n5.0,6.0,0\n")
    X, Y = read_csv(str(p))
    assert X.shape == (3, 2)
    np.testing.assert_array_equal(Y, [-1, 1, -1])


def test_csv_n_limit(tmp_path):
    # gpu_svm_main4.cu:38-40 row cap
    p = tmp_path / "d.csv"
    write_csv(str(p), np.arange(10.0).reshape(5, 2), np.ones(5, np.int32))
    X, Y = read_csv(str(p), n_limit=3)
    assert len(Y) == 3


def test_scaler_matches_reference_semantics():
    X = np.array([[0.0, 5.0, 7.0], [10.0, 5.0, 3.0], [5.0, 5.0, 5.0]])
    s = MinMaxScaler().fit(X)
    Xs = s.transform(X)
    # normal feature scaled to [0,1]
    np.testing.assert_allclose(Xs[:, 0], [0.0, 1.0, 0.5])
    # degenerate range (< 1e-12) -> divide by 1.0, i.e. x - min (main3.cpp:80-82)
    np.testing.assert_allclose(Xs[:, 1], [0.0, 0.0, 0.0])
    np.testing.assert_allclose(Xs[:, 2], [1.0, 0.0, 0.5])


def test_scaler_test_set_uses_train_minmax():
    Xtr = np.array([[0.0], [10.0]])
    Xte = np.array([[20.0]])
    s = MinMaxScaler().fit(Xtr)
    np.testing.assert_allclose(s.transform(Xte), [[2.0]])  # may leave [0,1]


def test_partition_contiguous_with_ids():
    X = np.arange(14.0).reshape(7, 2)
    Y = np.array([1, -1, 1, -1, 1, -1, 1], np.int32)
    part = partition(X, Y, 4)  # cap = ceil(7/4) = 2
    assert part.X.shape == (4, 2, 2)
    np.testing.assert_array_equal(part.count, [2, 2, 2, 1])
    np.testing.assert_array_equal(part.ids[0], [0, 1])
    np.testing.assert_array_equal(part.ids[3], [6, -1])
    assert part.valid[3, 1] == False  # noqa: E712
    # padded row is zeroed and label 0 (neither class)
    assert part.Y[3, 1] == 0
    np.testing.assert_array_equal(part.X[3, 1], [0.0, 0.0])
    # reassembling valid rows in order gives back the original data
    np.testing.assert_array_equal(part.X[part.valid], X)


def test_partition_stratified_balances_sorted_labels():
    # label-sorted input: the contiguous split hands out single-class
    # shards (the pallas-mp-adv fuzz shape); stratified must not
    n = 64
    X = np.arange(n * 2, dtype=float).reshape(n, 2)
    Y = np.array([1] * 32 + [-1] * 32, np.int32)
    cont = partition(X, Y, 4)
    assert any(len(np.unique(cont.Y[p][cont.valid[p]])) == 1
               for p in range(4))
    strat = partition(X, Y, 4, stratified=True)
    for p in range(4):
        ys = strat.Y[p][strat.valid[p]]
        assert set(np.unique(ys)) == {1, -1}
        assert (ys == 1).sum() == 8 and (ys == -1).sum() == 8
    # global IDs are still original row indices: reassembling by ID gives
    # back the dataset exactly (the cascade's dedup-by-ID contract)
    ids = strat.ids[strat.valid]
    np.testing.assert_array_equal(np.sort(ids), np.arange(n))
    np.testing.assert_array_equal(X[ids], strat.X[strat.valid])
    np.testing.assert_array_equal(Y[ids], strat.Y[strat.valid])


def test_partition_stratified_remainders_staggered():
    # 7 rows of class A, 5 of class B over 4 shards: per-class remainders
    # must not all land on shard 0 (class starts are staggered)
    Y = np.array([1] * 7 + [-1] * 5, np.int32)
    X = np.zeros((12, 3))
    part = partition(X, Y, 4, stratified=True)
    assert part.count.sum() == 12
    assert part.count.max() - part.count.min() <= 1
    # every shard still sees both classes where it has >= 2 rows
    for p in range(4):
        ys = part.Y[p][part.valid[p]]
        if len(ys) >= 2:
            assert len(np.unique(ys)) == 2


def test_synthetic_deterministic():
    X1, Y1 = blobs(n=50, seed=3)
    X2, Y2 = blobs(n=50, seed=3)
    np.testing.assert_array_equal(X1, X2)
    np.testing.assert_array_equal(Y1, Y2)
    Xm, Ym = mnist_like(n=100, d=32, rank=4, seed=1)
    assert Xm.shape == (100, 32)
    assert Xm.min() >= 0 and Xm.max() <= 255
    assert set(np.unique(Ym)) == {-1, 1}


def test_scaler_from_stats_bit_parity_with_fit():
    # per-shard partial min/max merged -> from_stats must transform
    # BIT-identically to fit() on the concatenated array, including the
    # degenerate-range (< 1e-12) branch (a constant column and a
    # sub-threshold-range column)
    from tpusvm.data import merge_minmax

    rng = np.random.default_rng(7)
    shards = []
    for i in range(5):
        S = rng.standard_normal((17 + i, 4))
        S[:, 1] = 3.25            # exactly constant: range 0
        S[:, 2] = 1.0 + rng.uniform(0, 0.9e-12, len(S))  # degenerate range
        shards.append(S)
    X = np.concatenate(shards)
    fitted = MinMaxScaler().fit(X)
    lo, hi = merge_minmax(
        (np.min(s, axis=0), np.max(s, axis=0)) for s in shards
    )
    merged = MinMaxScaler.from_stats(lo, hi)
    assert merged.min_val.tobytes() == fitted.min_val.tobytes()
    assert merged.max_val.tobytes() == fitted.max_val.tobytes()
    Xt = rng.standard_normal((13, 4))
    assert merged.transform(Xt).tobytes() == fitted.transform(Xt).tobytes()
    # the degenerate branch really engaged (range treated as 1.0)
    assert fitted.range_[1] == 1.0 and fitted.range_[2] == 1.0


def test_scaler_from_stats_validates():
    from tpusvm.data import merge_minmax

    with pytest.raises(ValueError):
        MinMaxScaler.from_stats(np.zeros(3), np.zeros(2))
    with pytest.raises(ValueError):
        MinMaxScaler.from_stats(np.ones(2), np.zeros(2))  # max < min
    with pytest.raises(ValueError):
        merge_minmax([])


def test_read_csv_blocks_matches_read_csv(tmp_path):
    from tpusvm.data import read_csv, read_csv_blocks, write_csv

    rng = np.random.default_rng(5)
    X = rng.standard_normal((97, 3))
    Y = rng.integers(0, 5, 97).astype(np.int32)
    p = str(tmp_path / "d.csv")
    write_csv(p, X, Y)
    for kw in ({}, {"n_limit": 41}, {"binary": False},
               {"positive_label": 3}):
        whole = read_csv(p, **kw)
        for block_rows in (1, 7, 97, 1000):
            blocks = list(read_csv_blocks(p, block_rows=block_rows, **kw))
            assert all(len(b[1]) <= block_rows for b in blocks)
            np.testing.assert_array_equal(
                np.concatenate([b[0] for b in blocks]), whole[0])
            np.testing.assert_array_equal(
                np.concatenate([b[1] for b in blocks]), whole[1])


def test_read_csv_blocks_header_only(tmp_path):
    from tpusvm.data import read_csv_blocks

    p = tmp_path / "h.csv"
    p.write_text("a,b,label\n")
    assert list(read_csv_blocks(str(p))) == []
