"""tpusvm.approx — the approximate-kernel primal regime (ISSUE 13).

Covers the four correctness claims the subsystem makes:
  * deterministic maps: same (seed, shape, gamma) -> bit-identical
    parameters and features, on every path that produces them (direct
    transform, the reader's prefetch hook, a reloaded model);
  * config-time validation: tile-misaligned map dims rejected up front
    (the JXIR104 padding-waste rationale applied at config time);
  * exact-oracle quality: rff/nystrom held-out accuracy within the fuzz
    band of the exact rbf solver on the same instance;
  * the serving/serialization contract: v4 roundtrips predict without
    retraining the map, serve's bucket cache scores bit-identically to
    the offline decision_function, v1-pattern states still load;
plus the interop matrix: streamed primal training under the residency
bound, fleet/ovr sharing one map, tune/fleet/stream-cascade rejecting
with specific named errors.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpusvm.config import APPROX_FAMILIES, SVMConfig, validate_map_dim
from tpusvm.data import MinMaxScaler, rings
from tpusvm.models import BinarySVC, EpsilonSVR, OneVsRestSVC, load_any


def _ring_split(n=640, n_test=160, seed=7):
    X, Y = rings(n=n + n_test, seed=seed)
    return X[:n], Y[:n], X[n:], Y[n:]


def _cfg(family, **kw):
    base = dict(C=10.0, gamma=10.0, kernel=family, map_seed=5)
    if family == "rff":
        base.setdefault("rff_dim", 512)
    else:
        base.setdefault("landmarks", 128)
    base.update(kw)
    return SVMConfig(**base)


# ------------------------------------------------------------- determinism
def test_rff_omega_deterministic_and_seed_sensitive():
    from tpusvm.approx import rff_omega

    a = rff_omega(16, 256, 0.5, seed=3)
    b = rff_omega(16, 256, 0.5, seed=3)
    c = rff_omega(16, 256, 0.5, seed=4)
    assert a.shape == (16, 128) and a.dtype == np.float32
    assert np.array_equal(a, b)
    assert not np.array_equal(a, c)


def test_transform_bit_identical_across_paths():
    # the SAME rows must map to the SAME bytes whether the map runs
    # directly, through the reader's prefetch hook wrapper, or from a
    # model reloaded off disk (the ingest/train/predict/serve contract)
    from tpusvm.approx import build_map

    Xtr, Ytr, _, _ = _ring_split()
    Xs = MinMaxScaler().fit_transform(Xtr).astype(np.float32)
    for family in APPROX_FAMILIES:
        fmap = build_map(_cfg(family), X_scaled=Xs)
        fmap2 = build_map(_cfg(family), X_scaled=Xs)
        z1 = fmap.transform_np(Xs)
        z2 = fmap2.transform_np(Xs)
        assert np.array_equal(z1, z2), family
        assert z1.shape == (len(Xs), fmap.dim)


def test_nystrom_landmark_indices_deterministic_and_bounded():
    from tpusvm.approx import nystrom_landmark_indices

    i1 = nystrom_landmark_indices(1000, 128, 9)
    i2 = nystrom_landmark_indices(1000, 128, 9)
    assert np.array_equal(i1, i2)
    assert len(set(i1.tolist())) == 128
    with pytest.raises(ValueError, match="landmarks <= n"):
        nystrom_landmark_indices(100, 128, 9)


def test_kernel_error_decreases_with_D():
    from tpusvm.approx import build_map, kernel_approx_error

    Xtr, _, _, _ = _ring_split()
    Xs = MinMaxScaler().fit_transform(Xtr).astype(np.float32)
    errs = []
    for D in (128, 512, 2048):
        fm = build_map(_cfg("rff", rff_dim=D), X_scaled=Xs)
        errs.append(kernel_approx_error(Xs, fm, 10.0))
    assert errs[0] > errs[1] > errs[2]
    assert errs[2] < 0.08


# ------------------------------------------------------ config validation
def test_tile_misaligned_map_dims_rejected_up_front():
    # the JXIR104 padding-waste rationale applied at CONFIG time: a D
    # off the 128-lane tile never reaches any data
    with pytest.raises(ValueError, match="not TPU-tile-aligned"):
        SVMConfig(kernel="rff", rff_dim=100)
    with pytest.raises(ValueError, match="not TPU-tile-aligned"):
        SVMConfig(kernel="nystrom", landmarks=100)
    with pytest.raises(ValueError, match="not TPU-tile-aligned"):
        validate_map_dim(64)
    # aligned dims pass; exact families never validate the map fields
    SVMConfig(kernel="rff", rff_dim=256)
    SVMConfig(kernel="rbf", rff_dim=100)


# ------------------------------------------------------ oracle-band quality
@pytest.mark.parametrize("family", APPROX_FAMILIES)
def test_approx_accuracy_within_band_of_exact_rbf(family):
    Xtr, Ytr, Xt, Yt = _ring_split()
    exact = BinarySVC(config=SVMConfig(C=10.0, gamma=10.0)).fit(Xtr, Ytr)
    approx = BinarySVC(config=_cfg(family)).fit(Xtr, Ytr)
    assert approx.status_.name == "CONVERGED"
    delta = exact.score(Xt, Yt) - approx.score(Xt, Yt)
    assert delta <= 0.055, f"{family}: accuracy delta {delta}"
    # mapped support rows: the model lives in the mapped space
    assert approx.sv_X_.shape[1] == approx.fmap_.dim
    assert approx.n_features_in_ == Xtr.shape[1]


# -------------------------------------------------- serialization contract
@pytest.mark.parametrize("family", APPROX_FAMILIES)
def test_v4_roundtrip_scores_bit_identical(family, tmp_path):
    Xtr, Ytr, Xt, _ = _ring_split()
    m = BinarySVC(config=_cfg(family)).fit(Xtr, Ytr)
    path = str(tmp_path / f"m_{family}.npz")
    m.save(path)
    with np.load(path) as z:
        assert int(z["format_version"]) == 4
        assert "map_n_features_in" in z.files
        if family == "nystrom":
            assert "map_landmarks" in z.files and "map_weights" in z.files
        else:
            # rff stores NO map arrays: (d, D, gamma, seed) regenerate
            assert "map_landmarks" not in z.files
    m2 = load_any(path)
    assert np.array_equal(m.decision_function(Xt),
                          m2.decision_function(Xt))
    assert np.array_equal(m.fmap_.arrays[0], m2.fmap_.arrays[0])


def test_v1_pattern_state_still_loads(tmp_path):
    # a v1-shaped artifact (no format bump beyond the recorded version,
    # no kernel/map config fields) must keep loading as implicit rbf
    path = str(tmp_path / "v1.npz")
    np.savez_compressed(
        path, format_version=1,
        sv_X=np.zeros((3, 4), np.float32), sv_Y=np.ones(3, np.int32),
        sv_alpha=np.ones(3), sv_ids=np.arange(3, dtype=np.int32),
        b=0.5, scale=False,
        config_C=10.0, config_gamma=0.5, config_tau=1e-5,
        config_eps=1e-12, config_sv_tol=1e-8, config_max_iter=1000,
        config_max_rounds=50,
    )
    m = BinarySVC.load(path)
    assert m.config.kernel == "rbf"
    assert m.fmap_ is None
    assert m.decision_function(np.zeros((2, 4))).shape == (2,)


def test_missing_map_provenance_fails_specifically(tmp_path):
    # an approx-family config whose state lost the map provenance must
    # fail by name, not as a downstream shape error
    from tpusvm.approx import map_from_state

    with pytest.raises(ValueError, match="map provenance"):
        map_from_state({}, _cfg("rff"))


# ------------------------------------------------------- serving contract
@pytest.mark.parametrize("family", APPROX_FAMILIES)
def test_serve_bucket_scores_bit_identical_to_offline(family, tmp_path):
    from tpusvm.serve.buckets import CompileCache, default_buckets
    from tpusvm.serve.registry import ModelEntry

    Xtr, Ytr, Xt, _ = _ring_split()
    m = BinarySVC(config=_cfg(family)).fit(Xtr, Ytr)
    path = str(tmp_path / f"serve_{family}.npz")
    m.save(path)
    entry = ModelEntry.from_path(family, path)
    # raw request width, NOT the mapped width
    assert entry.n_features == Xtr.shape[1]
    cache = CompileCache(entry, default_buckets(64))
    assert cache.warmup() > 0
    # m=3 lands in bucket 4, the geometry where a bucket-capped block
    # measurably drifted 1 ulp against offline (the reason the approx
    # buckets lower with the UNCAPPED block — serve/buckets.py)
    for rows in (Xt[:1], Xt[:3], Xt[:5], Xt[:64]):
        got, _ = cache.scores(entry.scale(entry.validate_rows(rows)))
        ref = m.decision_function(rows)
        assert np.array_equal(got, ref), (family, rows.shape)
    # steady state: no recompiles after warmup
    assert cache.recompiles == 0
    desc = entry.describe()
    assert desc["map_seed"] == 5 and desc["map_dim"] == m.fmap_.dim


# ------------------------------------------------------------- streaming
def _ingested(tmp_path, n=2048, seed=3, rows_per_shard=256):
    from tpusvm.stream import ingest_arrays, open_dataset

    X, Y = rings(n=n + 256, seed=seed)
    out = str(tmp_path / "ds")
    ingest_arrays(out, X[:n], Y[:n], rows_per_shard=rows_per_shard)
    return open_dataset(out), X[n:], Y[n:]


@pytest.mark.parametrize("family", APPROX_FAMILIES)
def test_streamed_primal_fit_bounded_residency(family, tmp_path):
    ds, Xt, Yt = _ingested(tmp_path)
    m = BinarySVC(config=_cfg(family),
                  solver_opts={"primal_epochs": 12, "primal_batch": 256})
    m.fit_stream(ds)
    # the residency bound: never more than prefetch_depth + 1 shards
    # resident, however many epochs re-stream the data
    assert m.stream_max_live_shards_ <= 3
    assert m.score(Xt, Yt) > 0.9
    # one-SV primal embedding serves through the standard layout
    assert m.n_support_ == 1
    assert m.sv_X_.shape == (1, m.fmap_.dim)


def test_streamed_features_match_in_memory_map(tmp_path):
    # the prefetch hook must produce the SAME bytes the in-memory path
    # maps: reader(transform=...) vs direct transform of scaled shards
    from tpusvm.approx import build_map
    from tpusvm.stream.reader import ShardReader

    ds, _, _ = _ingested(tmp_path, n=1024)
    scaler = ds.scaler()
    fmap = build_map(_cfg("rff"), n_features=ds.n_features)
    r = ShardReader(ds, scaler=scaler,
                    transform=lambda X: fmap.transform_np(X))
    mapped = np.concatenate([X for X, _ in r])
    direct = []
    for i in range(ds.n_shards):
        X, _ = ds.load_shard(i)
        direct.append(fmap.transform_np(scaler.transform(X)))
    assert np.array_equal(mapped, np.concatenate(direct))


def test_streamed_fit_rejects_blocked_knobs_and_checkpoint(tmp_path):
    ds, _, _ = _ingested(tmp_path, n=512)
    with pytest.raises(ValueError, match="primal knobs"):
        BinarySVC(config=_cfg("rff"),
                  solver_opts={"q": 64}).fit_stream(ds)
    with pytest.raises(ValueError, match="checkpoint"):
        BinarySVC(config=_cfg("rff")).fit_stream(
            ds, checkpoint_path=str(tmp_path / "ck.npz"))


# --------------------------------------------------------------- interop
def test_ovr_fleet_shares_one_map(tmp_path):
    from tpusvm.data.synthetic import mnist_like_multiclass

    X, L = mnist_like_multiclass(n=600, d=64, seed=1)
    cfg = SVMConfig(C=10.0, gamma=1.0 / 64, kernel="rff", rff_dim=256,
                    map_seed=2)
    m = OneVsRestSVC(config=cfg, solver="fleet", solver_opts={"q": 128})
    m.fit(X[:480], L[:480])
    assert m.score(X[480:], L[480:]) > 0.8
    assert m.X_sv_.shape[1] == m.fmap_.dim
    path = str(tmp_path / "ovr.npz")
    m.save(path)
    m2 = load_any(path)
    assert np.array_equal(m.decision_function(X[480:]),
                          m2.decision_function(X[480:]))


def test_svr_approx_fits_and_roundtrips(tmp_path):
    from tpusvm.data.synthetic import svr_sine

    X, t = svr_sine(n=400, d=2, seed=0)
    cfg = SVMConfig(C=10.0, gamma=20.0, epsilon=0.1, kernel="rff",
                    rff_dim=512)
    m = EpsilonSVR(config=cfg).fit(X[:320], t[:320])
    assert m.score(X[320:], t[320:]) > 0.9
    path = str(tmp_path / "svr.npz")
    m.save(path)
    m2 = load_any(path)
    assert np.array_equal(m.predict(X[320:]), m2.predict(X[320:]))


def test_tune_rejects_approx_families_by_name():
    from tpusvm.tune.search import normalize_kernel_specs

    with pytest.raises(ValueError, match="approximate kernel"):
        normalize_kernel_specs(["rbf", "rff"], SVMConfig())


def test_fleet_rejects_distinct_gammas_for_approx():
    from tpusvm.fleet import fleet_train

    X = jnp.zeros((32, 8), jnp.float32)
    Ys = [np.ones(32, np.int32), -np.ones(32, np.int32)]
    with pytest.raises(ValueError, match="single shared gamma"):
        fleet_train(X, Ys, [1.0, 1.0], [0.5, 1.0], kernel="rff")


def test_stream_cascade_rejects_approx_by_name(tmp_path):
    ds, _, _ = _ingested(tmp_path, n=512)
    with pytest.raises(ValueError, match="fit_cascade_stream"):
        BinarySVC(config=_cfg("rff")).fit_cascade_stream(ds)


def test_oracle_has_no_approx_kernel():
    from tpusvm.oracle.smo import kernel_row

    with pytest.raises(ValueError, match="oracle has no kernel"):
        kernel_row(np.zeros((4, 2)), np.zeros(2), _cfg("rff"))


# -------------------------------------------------------- sigmoid family
def test_sigmoid_matches_oracle():
    from tpusvm.data import blobs
    from tpusvm.oracle import get_sv_indices, smo_train
    from tpusvm.solver.blocked import blocked_smo_solve

    X, Y = blobs(n=240, d=6, seed=0)
    Xs = MinMaxScaler().fit_transform(X)
    cfg = SVMConfig(C=10.0, gamma=0.25, coef0=-1.0, kernel="sigmoid")
    o = smo_train(Xs, Y, cfg)
    assert o.status.name == "CONVERGED"
    r = blocked_smo_solve(
        jnp.asarray(Xs, jnp.float32), jnp.asarray(Y), q=64,
        C=cfg.C, gamma=cfg.gamma, coef0=cfg.coef0, kernel="sigmoid",
        accum_dtype=jnp.float64)
    sv_o = set(get_sv_indices(o.alpha).tolist())
    sv_r = set(get_sv_indices(np.asarray(r.alpha)).tolist())
    assert len(sv_o ^ sv_r) <= max(2, len(sv_o) // 25)
    assert abs(float(r.b) - o.b) <= 2e-3


def test_sigmoid_model_end_to_end(tmp_path):
    from tpusvm.data import blobs

    X, Y = blobs(n=300, d=6, seed=0)
    cfg = SVMConfig(C=10.0, gamma=0.25, coef0=-1.0, kernel="sigmoid")
    m = BinarySVC(config=cfg).fit(X[:240], Y[:240])
    assert m.score(X[240:], Y[240:]) > 0.9
    path = str(tmp_path / "sig.npz")
    m.save(path)
    m2 = load_any(path)
    assert np.array_equal(m.decision_function(X[240:]),
                          m2.decision_function(X[240:]))


# ------------------------------------------------------- ir-audit surface
def test_approx_entry_points_registered():
    import tpusvm.approx.features  # noqa: F401 — registers on import
    from tpusvm.analysis.ir.entrypoints import entrypoint_names
    from tpusvm.obs.prof import JIT_ENTRY_POINTS

    for name in ("approx.rff_transform", "approx.nystrom_transform",
                 "predict.approx_decision", "predict.approx_ovr_scores"):
        assert name in JIT_ENTRY_POINTS
        assert name in entrypoint_names()


@pytest.mark.slow
def test_streamed_512k_rows_bounded_residency(tmp_path):
    # the acceptance-scale claim: >= 512k streamed rows train on CPU
    # with bounded residency and no materialised (n, D) feature array
    # (peak memory = shards + one batch; asserted via the reader's
    # audited high-water mark)
    import tracemalloc

    from tpusvm.stream import ingest_arrays, open_dataset

    n, d = 524_288, 16
    rng = np.random.default_rng(0)
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal(d)
    Y = np.where(X @ w + 0.1 * rng.standard_normal(n) > 0, 1, -1)
    out = str(tmp_path / "big")
    ingest_arrays(out, X, Y, rows_per_shard=16_384)
    ds = open_dataset(out)
    cfg = SVMConfig(C=1.0, gamma=0.5, kernel="rff", rff_dim=128)
    m = BinarySVC(config=cfg, solver_opts={"primal_epochs": 3,
                                           "primal_batch": 4096})
    tracemalloc.start()
    m.fit_stream(ds)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert m.stream_max_live_shards_ <= 3
    # the full mapped matrix would be n * 128 * 4 = 256 MB of host
    # arrays; the bounded pipeline must stay far under it
    assert peak < 128 * 1024 * 1024, f"peak host alloc {peak}"
    assert m.score(X[:4096], Y[:4096]) > 0.9
