"""tpusvm.stream tests: format integrity, stats parity, reader residency,
assignment parity with data.partition, streamed train/predict parity.

The subsystem's whole claim is "same model, bounded memory": every parity
test here compares the streamed path against the in-memory path on the
SAME rows and demands byte equality (arrays) or exact equality (IDs, b,
accuracy) — not tolerances. The cascade end-to-end test needs
jax.shard_map and skips where the installed jax lacks it (the same
environments where tests/test_cascade.py cannot run).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import SVMConfig
from tpusvm.data import MinMaxScaler, partition, rings, write_csv
from tpusvm.data.csv_reader import read_csv
from tpusvm.status import StreamStatus
from tpusvm.stream import (
    ShardReader,
    assign_rows,
    evaluate_stream,
    gather_rows,
    ingest_arrays,
    ingest_csv,
    open_dataset,
    partition_from_dataset,
    predict_stream,
)

CFG = SVMConfig(C=10.0, gamma=10.0)


@pytest.fixture(scope="module")
def rings_data():
    return rings(n=257, seed=3)


@pytest.fixture()
def dataset(tmp_path, rings_data):
    X, Y = rings_data
    ingest_arrays(str(tmp_path / "ds"), X, Y, rows_per_shard=50)
    return open_dataset(str(tmp_path / "ds"))


# ------------------------------------------------------------------ format
def test_ingest_roundtrip_and_manifest(dataset, rings_data):
    X, Y = rings_data
    assert dataset.n_rows == 257 and dataset.n_features == 2
    assert dataset.n_shards == 6  # 5 x 50 + 7
    assert [s.n_rows for s in dataset.manifest.shards] == [50] * 5 + [7]
    assert [s.row_start for s in dataset.manifest.shards] == \
        [0, 50, 100, 150, 200, 250]
    Xr, Yr = dataset.load_arrays()
    assert Xr.tobytes() == np.ascontiguousarray(X).tobytes()
    np.testing.assert_array_equal(Yr, Y)
    np.testing.assert_array_equal(dataset.load_labels(), Y)
    assert all(s == StreamStatus.OK for s in dataset.validate())


def test_ingest_csv_matches_read_csv(tmp_path):
    # streamed CSV ingest (blocks never spanning the whole file) must
    # reproduce read_csv's rows exactly, short-row skips and n_limit
    # and positive_label mapping included
    p = str(tmp_path / "d.csv")
    rng = np.random.default_rng(0)
    X = rng.standard_normal((83, 4))
    Y = rng.integers(0, 4, 83).astype(np.int32)
    write_csv(p, X, Y)
    with open(p, "a") as f:
        f.write("9\n\n")  # short rows: skipped by both readers
    out = str(tmp_path / "ds")
    m = ingest_csv(out, p, rows_per_shard=16, block_rows=7,
                   positive_label=2)
    Xc, Yc = read_csv(p, positive_label=2)
    ds = open_dataset(out)
    Xr, Yr = ds.load_arrays()
    assert Xr.tobytes() == Xc.tobytes()
    np.testing.assert_array_equal(Yr, Yc)
    assert m.positive_label == 2 and m.binary
    m2 = ingest_csv(str(tmp_path / "ds2"), p, rows_per_shard=16,
                    n_limit=20, binary=False)
    ds2 = open_dataset(str(tmp_path / "ds2"))
    assert ds2.n_rows == 20
    np.testing.assert_array_equal(ds2.load_labels(),
                                  read_csv(p, n_limit=20, binary=False)[1])
    assert m2.positive_label is None and not m2.binary


def test_manifest_version_gate(tmp_path, rings_data):
    X, Y = rings_data
    out = str(tmp_path / "ds")
    ingest_arrays(out, X, Y, rows_per_shard=100)
    mpath = os.path.join(out, "manifest.json")
    obj = json.load(open(mpath))
    obj["format_version"] = 99
    json.dump(obj, open(mpath, "w"))
    with pytest.raises(ValueError, match="format_version"):
        open_dataset(out)
    del obj["format_version"]
    json.dump(obj, open(mpath, "w"))
    with pytest.raises(ValueError, match="format_version"):
        open_dataset(out)
    with pytest.raises(FileNotFoundError, match="ingest"):
        open_dataset(str(tmp_path / "nowhere"))


def test_validate_statuses(tmp_path, rings_data):
    X, Y = rings_data
    out = str(tmp_path / "ds")
    ingest_arrays(out, X, Y, rows_per_shard=64)
    ds = open_dataset(out)

    # CHECKSUM_MISMATCH: flip one value, keep shape
    with np.load(ds.shard_path(1)) as z:
        Xs, Ys = z["X"].copy(), z["Y"]
        Xs[0, 0] += 1.0
        np.savez(ds.shard_path(1), X=Xs, Y=Ys)
    # MISSING_FILE
    os.remove(ds.shard_path(2))
    statuses = ds.validate()
    assert statuses[0] == StreamStatus.OK
    assert statuses[1] == StreamStatus.CHECKSUM_MISMATCH
    assert statuses[2] == StreamStatus.MISSING_FILE

    # ROW_COUNT_MISMATCH: manifest claims more rows than the file holds
    ds.manifest.shards[3].stats.n_rows += 1
    assert ds.validate()[3] == StreamStatus.ROW_COUNT_MISMATCH
    ds.manifest.shards[3].stats.n_rows -= 1

    # STATS_MISMATCH: stats lie but the checksum (content) still matches
    ds.manifest.shards[3].stats.min_val = \
        ds.manifest.shards[3].stats.min_val - 1.0
    assert ds.validate()[3] == StreamStatus.STATS_MISMATCH

    # load_shard(verify=True) raises on a tampered shard
    with pytest.raises(ValueError, match="CHECKSUM_MISMATCH"):
        ds.load_shard(1, verify=True)


def test_ingest_refuses_empty_and_ragged(tmp_path):
    from tpusvm.stream import ShardWriter

    with pytest.raises(ValueError, match="empty"):
        with ShardWriter(str(tmp_path / "e")) as w:
            pass
    w = ShardWriter(str(tmp_path / "r"))
    w.append(np.zeros((3, 4)), np.ones(3, np.int32))
    with pytest.raises(ValueError, match="feature count"):
        w.append(np.zeros((3, 5)), np.ones(3, np.int32))


# ------------------------------------------------------------------- stats
def test_scaler_from_manifest_bit_parity(tmp_path):
    # includes a constant column and a sub-1e-12-range column so the
    # degenerate branch is part of the proof
    rng = np.random.default_rng(1)
    X = rng.uniform(-5, 5, (211, 5))
    X[:, 2] = -2.5
    X[:, 3] = 7.0 + rng.uniform(0, 0.5e-12, 211)
    Y = np.where(rng.random(211) < 0.5, 1, -1).astype(np.int32)
    out = str(tmp_path / "ds")
    ingest_arrays(out, X, Y, rows_per_shard=37)
    ds = open_dataset(out)
    sc, sf = ds.scaler(), MinMaxScaler().fit(X)
    assert sc.min_val.tobytes() == sf.min_val.tobytes()
    assert sc.max_val.tobytes() == sf.max_val.tobytes()
    assert sc.transform(X).tobytes() == sf.transform(X).tobytes()
    stats = ds.stats()
    assert stats.n_rows == 211
    assert stats.class_counts == {
        -1: int((Y == -1).sum()), 1: int((Y == 1).sum())}


# ------------------------------------------------------------------ reader
def test_reader_roundtrip_and_deterministic_order(dataset, rings_data):
    X, Y = rings_data
    blocks = list(ShardReader(dataset))
    assert np.array_equal(np.concatenate([b[0] for b in blocks]), X)
    assert np.array_equal(np.concatenate([b[1] for b in blocks]), Y)
    r1 = ShardReader(dataset, seed=42)
    r2 = ShardReader(dataset, seed=42)
    np.testing.assert_array_equal(r1.shard_order, r2.shard_order)
    assert not np.array_equal(ShardReader(dataset, seed=1).shard_order,
                              ShardReader(dataset, seed=2).shard_order)
    # a shuffled read is a permutation of the same rows
    got = np.concatenate([b[1] for b in r1])
    assert sorted(got.tolist()) == sorted(Y.tolist())


def test_reader_residency_bound(dataset):
    # the acceptance hook: with a deliberately slow consumer the producer
    # must never hold more than prefetch_depth + 1 shards resident
    for depth in (1, 2):
        r = ShardReader(dataset, prefetch_depth=depth)
        for _ in r:
            time.sleep(0.01)  # let the producer run far ahead if it can
        assert r.max_live_shards <= depth + 1
        assert r.live_shards == 0  # everything released on completion


def test_reader_scaling_on_the_fly(dataset, rings_data):
    X, _ = rings_data
    sc = dataset.scaler()
    blocks = list(ShardReader(dataset, scaler=sc, dtype=np.float32))
    got = np.concatenate([b[0] for b in blocks])
    want = sc.transform(X).astype(np.float32)
    assert got.dtype == np.float32
    assert got.tobytes() == want.tobytes()


def test_reader_batches_rechunk(dataset, rings_data):
    X, Y = rings_data
    for bs in (1, 32, 50, 64, 257, 1000):
        got = list(ShardReader(dataset).batches(bs))
        assert all(len(b[1]) == bs for b in got[:-1])
        assert 0 < len(got[-1][1]) <= bs
        assert np.array_equal(np.concatenate([b[0] for b in got]), X)
        assert np.array_equal(np.concatenate([b[1] for b in got]), Y)


def test_reader_single_pass_and_error_propagation(tmp_path, rings_data):
    X, Y = rings_data
    out = str(tmp_path / "ds")
    ingest_arrays(out, X, Y, rows_per_shard=64)
    ds = open_dataset(out)
    r = ShardReader(ds)
    list(r)
    with pytest.raises(RuntimeError, match="single-pass"):
        iter(r).__next__()
    # corrupt a shard: verify=True must surface the error in the consumer
    with np.load(ds.shard_path(1)) as z:
        Xs, Ys = z["X"].copy(), z["Y"]
    Xs[0, 0] += 1.0
    np.savez(ds.shard_path(1), X=Xs, Y=Ys)
    with pytest.raises(ValueError, match="CHECKSUM_MISMATCH"):
        list(ShardReader(ds, verify=True))


# ------------------------------------------------------------------ assign
@pytest.mark.parametrize("n,P", [(257, 4), (64, 8), (7, 4), (12, 5),
                                 (100, 1), (5, 8)])
@pytest.mark.parametrize("stratified", [False, True])
def test_assign_rows_matches_partition(n, P, stratified):
    rng = np.random.default_rng(n * 31 + P)
    X = rng.standard_normal((n, 3))
    Y = np.where(rng.random(n) < 0.4, 1, -1).astype(np.int32)
    ref = partition(X, Y, P, stratified=stratified)
    asg = assign_rows(n, P, Y=Y if stratified else None,
                      stratified=stratified)
    assert asg.cap == ref.X.shape[1]
    np.testing.assert_array_equal(asg.count, ref.count)
    # scatter by (part, slot) and compare against the reference fill
    Xp = np.zeros_like(ref.X)
    Yp = np.zeros_like(ref.Y)
    ids = np.full_like(ref.ids, -1)
    valid = np.zeros_like(ref.valid)
    g = np.arange(n)
    Xp[asg.part, asg.slot] = X
    Yp[asg.part, asg.slot] = Y
    ids[asg.part, asg.slot] = g
    valid[asg.part, asg.slot] = True
    np.testing.assert_array_equal(Xp, ref.X)
    np.testing.assert_array_equal(Yp, ref.Y)
    np.testing.assert_array_equal(ids, ref.ids)
    np.testing.assert_array_equal(valid, ref.valid)


def test_assign_stratified_needs_labels():
    with pytest.raises(ValueError, match="labels"):
        assign_rows(10, 2, stratified=True)


@pytest.mark.parametrize("stratified", [False, True])
def test_partition_from_dataset_bit_identical(dataset, rings_data,
                                              stratified):
    # the cascade-leaf acceptance: streaming shards into the partition
    # (with the manifest-fitted scaler) equals make_partition on the
    # scaled full array, field for field, byte for byte
    X, Y = rings_data
    sc = dataset.scaler()
    ref = partition(sc.transform(X), Y, 4, stratified=stratified)
    got = partition_from_dataset(dataset, 4, stratified=stratified,
                                 scaler=sc)
    for name, a, b in zip(ref._fields, ref, got):
        assert a.tobytes() == b.tobytes(), name


def test_gather_rows(dataset, rings_data):
    X, _ = rings_data
    rng = np.random.default_rng(9)
    idx = rng.permutation(257)[:90]
    assert gather_rows(dataset, idx).tobytes() == \
        np.ascontiguousarray(X[idx]).tobytes()
    assert gather_rows(dataset, np.arange(0)).shape == (0, 2)
    with pytest.raises(IndexError):
        gather_rows(dataset, [257])


# ------------------------------------------------- streamed train / predict
def test_fit_stream_parity(dataset, rings_data):
    from tpusvm.models import BinarySVC

    X, Y = rings_data
    m1 = BinarySVC(config=CFG).fit(X, Y)
    m2 = BinarySVC(config=CFG).fit_stream(dataset)
    np.testing.assert_array_equal(m1.sv_ids_, m2.sv_ids_)
    assert m1.b_ == m2.b_
    assert m1.n_iter_ == m2.n_iter_
    assert m2.scaler_.min_val.tobytes() == m1.scaler_.min_val.tobytes()
    np.testing.assert_array_equal(m1.sv_alpha_, m2.sv_alpha_)


def test_predict_and_evaluate_stream_parity(dataset, rings_data):
    from tpusvm.models import BinarySVC

    X, Y = rings_data
    model = BinarySVC(config=CFG).fit(X, Y)
    want = np.asarray(model.decision_function(X))
    chunks = list(predict_stream(model, dataset, batch_size=60))
    got = np.concatenate([s for s, _ in chunks])
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(np.concatenate([y for _, y in chunks]), Y)

    acc, n = evaluate_stream(model, dataset, batch_size=60)
    assert n == 257
    assert acc == model.score(X, Y)

    acc_lim, n_lim = evaluate_stream(model, dataset, batch_size=60,
                                     n_limit=100)
    assert n_lim == 100
    assert acc_lim == float(
        (np.asarray(model.predict(X[:100])) == Y[:100]).mean())


def test_streamed_cascade_parity(tmp_path, rings_data):
    # THE acceptance test: manifest-fitted scaler + shard-assigned leaves
    # must train the identical cascade model to the in-memory array path.
    # Runs on plain CPU jax: cascade_fit's host fallback executes the
    # same round functions without shard_map when the mesh is absent.
    from tpusvm.config import CascadeConfig
    from tpusvm.models import BinarySVC

    X, Y = rings_data
    Xt, Yt = rings(n=64, seed=99)
    out = str(tmp_path / "ds")
    ingest_arrays(out, X, Y, rows_per_shard=48)
    ds = open_dataset(out)
    cc = CascadeConfig(n_shards=4, sv_capacity=192)
    for stratified in (False, True):
        m1 = BinarySVC(config=CFG, dtype=jnp.float64).fit_cascade(
            X, Y, cc, stratified=stratified)
        m2 = BinarySVC(config=CFG, dtype=jnp.float64).fit_cascade_stream(
            ds, cc, stratified=stratified)
        assert sorted(m1.sv_ids_.tolist()) == sorted(m2.sv_ids_.tolist())
        assert m1.b_ == m2.b_
        assert m1.cascade_rounds_ == m2.cascade_rounds_
        assert m1.score(Xt, Yt) == m2.score(Xt, Yt)


def test_tune_dataset_parity(dataset, rings_data):
    # folds resolvable from a manifest: identical table to in-memory tune
    from tpusvm.tune import TuneConfig, make_grid, tune

    X, Y = rings_data
    grid = make_grid([1.0, 8.0], [1.0, 8.0])
    cfg = TuneConfig(folds=2, seed=0)
    r1 = tune(X, Y, grid, cfg, base=SVMConfig())
    r2 = tune(None, None, grid, cfg, base=SVMConfig(), dataset=dataset)
    assert r1.winner == r2.winner
    for a, b in zip(r1.points, r2.points):
        assert a["cv_accuracy"] == b["cv_accuracy"]
        assert a["n_updates"] == b["n_updates"]
        assert a["fold_accuracy"] == b["fold_accuracy"]
    with pytest.raises(ValueError, match="not both"):
        tune(X, Y, grid, cfg, dataset=dataset)


# --------------------------------------------------------------------- cli
def test_cli_ingest_train_predict_roundtrip(tmp_path, capsys, rings_data):
    from tpusvm.cli import main
    from tpusvm.models import BinarySVC

    X, Y = rings_data
    csv = str(tmp_path / "d.csv")
    write_csv(csv, X, Y)
    out = str(tmp_path / "ds")
    rc = main(["ingest", "--train", csv, "--out", out,
               "--rows-per-shard", "64", "-q"])
    assert rc == 0
    model = str(tmp_path / "m.npz")
    rc = main(["train", "--data", out, "--C", "10", "--gamma", "10",
               "--save", model, "-q"])
    assert rc == 0
    capsys.readouterr()
    rc = main(["predict", "--model", model, "--data", out,
               "--batch-size", "100"])
    assert rc == 0
    streamed = capsys.readouterr().out
    # streamed accuracy line == in-memory accuracy on the same rows
    m = BinarySVC.load(model)
    acc = m.score(X, Y)
    assert f"accuracy = {acc:.4f} ({round(acc * len(Y))}/{len(Y)})" \
        in streamed

    rc = main(["info", out])
    assert rc == 0
    assert "validation: all" in capsys.readouterr().out

    rc = main(["ingest", "--smoke", "-q"])
    assert rc == 0


def test_cli_ingest_smoke_gate(capsys):
    from tpusvm.cli import main

    assert main(["ingest", "--smoke"]) == 0
    assert "ingest smoke ok" in capsys.readouterr().out


def test_cli_train_data_flag_validation(tmp_path, rings_data):
    from tpusvm.cli import main

    X, Y = rings_data
    out = str(tmp_path / "ds")
    ingest_arrays(out, X, Y)
    with pytest.raises(SystemExit, match="exactly one"):
        main(["train", "--data", out, "--synthetic", "rings"])
    with pytest.raises(SystemExit, match="n-limit|n_limit|manifest"):
        main(["train", "--data", out, "--n-limit", "10"])
    with pytest.raises(SystemExit, match="oracle"):
        main(["train", "--data", out, "--mode", "oracle"])
