"""Smoke coverage for the benchmark harnesses.

The reference's scaling studies (report Tables 2-4) are reproduced by
benchmarks/sweep_n.py and benchmarks/sweep_p.py; these tests keep the
harnesses runnable (arg plumbing, emitted-record schema) on the simulated
mesh without timing anything.
"""

import json

import pytest


def _records(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    return [json.loads(line) for line in out if line.startswith("{")]


def test_sweep_p_smoke_schema_and_convergence(capsys):
    from benchmarks import sweep_p

    # --platform native: the pytest process already runs on the 8-device
    # simulated CPU mesh (conftest), so don't touch backend config
    rc = sweep_p.main([
        "--platform", "native", "--n", "512", "--n-test", "128",
        "--d", "32", "--shards", "2", "--topologies", "tree", "star",
        "--sv-capacity", "256", "--gamma", "0.03125",
    ])
    assert rc == 0
    recs = _records(capsys)
    assert len(recs) == 2  # tree P=2, star P=2
    first_ids_claimed = None
    for r in recs:
        assert r["converged"]
        assert r["rounds"] >= 1
        assert len(r["per_round"]) == r["rounds"]
        assert 0.0 <= r["round1_sv_fraction"] <= 1.0
        assert 0.0 <= r["sv_jaccard_vs_first"] <= 1.0
        assert 0.0 <= r["accuracy"] <= 1.0
        assert r["n_sv"] > 0
    # the first record IS the parity baseline
    assert recs[0]["sv_set_match_vs_first"]
    assert recs[0]["sv_jaccard_vs_first"] == 1.0


def test_sweep_n_smoke_schema(capsys):
    from benchmarks import sweep_n

    rc = sweep_n.main(["--sizes", "384", "--n-test", "128", "--d", "32",
                       "--gamma", "0.03125", "--q", "128",
                       "--max-inner", "128"])
    assert rc == 0
    recs = _records(capsys)
    assert len(recs) == 1
    r = recs[0]
    assert r["n"] == 384
    # provenance contract (ADVICE r5): workload.n stays the GENERATOR'S n
    # (mnist_like is not prefix-stable in n), and n_train records the
    # trained prefix separately
    assert r["workload"]["synthetic"] is True
    assert r["workload"]["n"] == 384 + 128  # n_max + n_test, as generated
    assert r["workload"]["n_train"] == 384
    assert r["train_s"] > 0 and r["predict_s"] > 0
    assert r["predict_all_n_s"] > 0  # the like-for-like C16 semantics time
    assert 0.0 <= r["accuracy"] <= 1.0
    assert r["n_sv"] > 0
    # sizes outside the reference's table carry no vs_gpu_* ratios
    assert r["vs_gpu_train"] is None


def test_ovr_10class_smoke_schema(capsys):
    from benchmarks import ovr_10class

    rc = ovr_10class.main(["--n", "400", "--n-test", "100", "--d", "32",
                           "--gamma", "0.03125"])
    assert rc == 0
    recs = _records(capsys)
    assert len(recs) == 1
    r = recs[0]
    assert r["classes"] == 10
    assert 0.0 <= r["accuracy"] <= 1.0
    assert r["train_s"] > 0
    assert r["n_sv_union"] > 0


def test_fuzz_parity_smoke_schema(capsys):
    # two random instances through all five engines vs the oracle: keeps
    # the fuzz harness runnable and its verdict logic honest (two
    # committed 64-case batches live in
    # benchmarks/results/fuzz_parity_cpu.jsonl)
    from benchmarks import fuzz_parity

    rc = fuzz_parity.main(2, 4242)
    recs = _records(capsys)
    assert len(recs) == 3  # 2 cases + summary
    summary = recs[-1]
    assert summary["summary"] is True
    assert rc == 0 and summary["violations"] == 0
    for rec in recs[:-1]:
        if rec.get("skipped"):
            continue
        assert set(rec["engines"]) == {
            "pair-f64", "blocked-exact", "blocked-approx",
            "blocked-exact-wss2", "blocked-approx-wss2"}
        for verdict in rec["engines"].values():
            assert verdict["ok"]


@pytest.mark.parametrize("mode,seed,engines", [
    ("pallas", 5000,
     {"pair-f64", "blocked-pallas-wss1", "blocked-pallas-wss2",
      "blocked-pallas-wss2-etax"}),
    ("pallas-packed", 7000,
     {"pair-f64", "blocked-pallas-wss1", "blocked-pallas-wss2",
      "blocked-pallas-wss2-etax"}),
    ("pallas-mp", 9000,
     {"pair-f64", "blocked-pallas-wss1", "blocked-pallas-mp2"}),
    # round 6: the ADVICE r5 #4 adversarial family (block-sorted labels
    # + duplicated rows) through the same multipair engine grid, with
    # duplicate-group SV comparison
    ("pallas-mp-adv", 9100,
     {"pair-f64", "blocked-pallas-wss1", "blocked-pallas-mp2"}),
])
def test_fuzz_parity_pallas_mode_smoke(capsys, mode, seed, engines):
    # one random instance through the PALLAS inner engine (interpret off
    # TPU — the kernel every TPU headline runs) vs the oracle: keeps the
    # pallas fuzz modes runnable — q=128 (R=1, flat-equivalent), q=256
    # (R=2, the genuine multi-row packed layout; both since round 5 also
    # covering the eta_exclude unified-selection kernel), and q=512
    # (the smallest valid p=2 slot partition for the multipair kernel) —
    # committed 64-case batches in
    # benchmarks/results/fuzz_parity_pallas_cpu.jsonl
    from benchmarks import fuzz_parity

    rc = fuzz_parity.main(1, seed, mode)
    recs = _records(capsys)
    assert len(recs) == 2  # 1 case + summary
    summary = recs[-1]
    assert summary["mode"] == mode
    assert rc == 0 and summary["violations"] == 0
    rec = recs[0]
    if not rec.get("skipped"):
        assert set(rec["engines"]) == engines
        for verdict in rec["engines"].values():
            assert verdict["ok"]


@pytest.mark.parametrize("mode,seed,engines", [
    # the kernel/task matrix modes (ISSUE 6): linear includes the
    # generic-K-row-path engine so fast-vs-generic equal-solutions
    # evidence rides every batch; committed 64-case batches in
    # benchmarks/results/fuzz_parity_kernels_cpu.jsonl
    ("linear", 11000,
     {"pair-f64", "blocked-exact", "blocked-exact-wss2",
      "blocked-generic-path"}),
    ("poly", 12000,
     {"pair-f64", "blocked-exact", "blocked-exact-wss2"}),
    ("svr", 13000,
     {"pair-f64", "blocked-exact", "blocked-exact-wss2"}),
])
def test_fuzz_parity_kernel_mode_smoke(capsys, mode, seed, engines):
    from benchmarks import fuzz_parity

    rc = fuzz_parity.main(1, seed, mode)
    recs = _records(capsys)
    assert len(recs) == 2  # 1 case + summary
    summary = recs[-1]
    assert summary["mode"] == mode
    assert rc == 0 and summary["violations"] == 0
    rec = recs[0]
    assert rec["scenario"] == mode
    if not rec.get("skipped"):
        assert set(rec["engines"]) == engines
        for verdict in rec["engines"].values():
            assert verdict["ok"]


def test_kernel_matrix_smoke_schema(capsys):
    # the linear-fast-path benchmark (ISSUE 6): schema + the
    # load-independent hard gates — every engine converged and the
    # fast/generic linear pair at EQUAL SOLUTIONS. The >= 1.5x speedup
    # floor is asserted only on the committed full-size run (a
    # smoke-shape CPU timing is pure noise)
    from benchmarks import kernel_matrix

    rc = kernel_matrix.main(["--smoke"])
    assert rc == 0
    recs = _records(capsys)
    rows = [r for r in recs if "summary" not in r]
    assert [r["engine"] for r in rows] == [
        "rbf", "poly-d2", "linear-generic", "linear-fast"]
    for r in rows:
        assert r["workload"]["synthetic"] is True
        assert r["status"] == "CONVERGED"
        assert r["wall_s"] > 0 and r["n_updates"] > 0 and r["n_sv"] > 0
    summary = recs[-1]
    assert summary["summary"] and summary["violations"] == []
    # the committed CPU grid carries the same schema AND clears the
    # acceptance gate this PR claims: >= 1.5x linear fast-path win over
    # the generic K-row path at equal solutions, on every cell
    import json as _json
    import os as _os

    path = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "benchmarks", "results",
        "kernel_matrix_cpu.jsonl")
    committed = [_json.loads(line) for line in open(path)]
    committed_rows = [r for r in committed if "summary" not in r]
    # - {"provenance"}: emitted rows self-describe their backend since
    # the performance-observatory round; committed artifacts predate it
    assert committed_rows and \
        set(rows[0]) - {"provenance"} <= set(committed_rows[0])
    full = committed[-1]
    assert full["summary"] and full["smoke"] is False
    assert full["violations"] == []
    assert full["speedup_gate"] == 1.5
    assert full["min_speedup"] >= 1.5
    assert len(full["linear_fast_speedups"]) == full["cells"] >= 3


def test_committed_kernel_fuzz_batches_are_clean():
    # the committed randomized parity evidence for every new (kernel,
    # task) cell: three 64-case batches, zero violations, f64 engines at
    # exact SV-set parity
    import json as _json
    import os as _os

    path = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "benchmarks", "results",
        "fuzz_parity_kernels_cpu.jsonl")
    recs = [_json.loads(line) for line in open(path)]
    summaries = [r for r in recs if r.get("summary")]
    assert {s["mode"] for s in summaries} == {"linear", "poly", "svr",
                                              "sigmoid"}
    for s in summaries:
        assert s["cases"] == 64 and s["violations"] == 0
    for r in recs:
        if r.get("summary") or r.get("skipped"):
            continue
        assert r["engines"]["pair-f64"]["sv_sym_diff"] == 0


def test_committed_approx_fuzz_batch_is_clean():
    # the committed accuracy-delta evidence for the approximate regime
    # (ISSUE 13): 32 cases, every arm CONVERGED within the band of the
    # exact rbf oracle's held-out accuracy, zero skips
    import json as _json
    import os as _os

    path = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "benchmarks", "results",
        "fuzz_parity_approx_cpu.jsonl")
    recs = [_json.loads(line) for line in open(path)]
    summary = [r for r in recs if r.get("summary")]
    assert len(summary) == 1 and summary[0]["violations"] == 0
    assert summary[0]["cases"] == 32
    for r in recs:
        if r.get("summary"):
            continue
        assert not r.get("skipped")
        for name, e in r["engines"].items():
            assert e["ok"], (r["seed"], name)
            assert e["acc_delta"] <= e["band"]


def test_serve_latency_smoke_schema(capsys):
    # the serving load-generator (ISSUE 2): schema + the hard gates that
    # are load-independent — zero errors and zero post-warm-up recompiles.
    # Throughput RATIOS are deliberately not asserted here: the smoke
    # model is tiny, so this run measures arg plumbing, not batching
    # economics (benchmarks/results/serve_latency_cpu.jsonl holds the
    # committed full-size curve: >= 3.8x at 8 threads)
    from benchmarks import serve_latency

    rc = serve_latency.main(["--smoke"])
    assert rc == 0
    recs = _records(capsys)
    assert recs[0]["mode"] == "sequential" and recs[0]["qps"] > 0
    batched = [r for r in recs if r.get("mode") == "batched"]
    assert [r["threads"] for r in batched] == [1, 8]
    for r in batched:
        assert r["errors"] == 0 and r["recompiles"] == 0
        assert r["not_ok"] == 0
        assert r["mean_batch_rows"] >= 1.0
        assert r["p50_ms"] is not None
        assert r["workload"]["synthetic"] is True
    summary = recs[-1]
    assert summary["summary"] and summary["violations"] == []


def test_tune_sweep_smoke_schema(capsys):
    # the cold-vs-warm tune A/B (ISSUE 3): schema + the load-independent
    # hard gates — identical winner and CV accuracy within 1e-6 between
    # the arms. The >= 30% savings floor is deliberately not asserted at
    # smoke shape (active-set transfer needs real SV counts);
    # benchmarks/results/tune_sweep_cpu.jsonl holds the committed
    # full-size curve: 43.8% total saving at n=768 d=64, 5x5 grid
    from benchmarks import tune_sweep

    rc = tune_sweep.main(["--smoke"])
    assert rc == 0
    recs = _records(capsys)
    points = [r for r in recs if "summary" not in r]
    assert len(points) == 4  # 2x2 smoke grid
    for r in points:
        assert r["workload"]["synthetic"] is True
        assert r["cold_updates"] > 0 and r["warm_updates"] > 0
        assert abs(r["cold_cv"] - r["warm_cv"]) <= 1e-6
    # the warm chain engages on every point after the first
    assert all(r["warm_seeded"] == 2 for r in points[1:])
    summary = recs[-1]
    assert summary["summary"] and summary["violations"] == []
    assert summary["same_winner"] is True
    assert summary["max_cv_diff"] <= 1e-6
    assert summary["warm_total_updates"] == sum(
        r["warm_updates"] for r in points)


def test_midsize_cascade_smoke(capsys):
    # the production-scale cascade artifact harness (VERDICT r4 #6),
    # shrunken: direct control + tree + star on the simulated mesh, zero
    # violations, schema stable (committed full-size run in
    # benchmarks/results/midsize_cascade_sim_cpu.jsonl)
    from benchmarks import midsize_cascade

    rc = midsize_cascade.main(["--smoke"])
    recs = _records(capsys)
    assert rc == 0
    assert [r.get("engine") for r in recs[:3]] == [
        "direct-blocked", "cascade-tree", "cascade-star"]
    summary = recs[-1]
    assert summary["summary"] and summary["violations"] == []
    for r in recs[1:3]:
        assert r["converged"]
        assert r["sv_jaccard_vs_direct"] >= 0.85
        assert r["workload"]["synthetic"] is True


def test_fuzz_cascade_smoke_schema(capsys):
    # one random instance through tree AND star vs a direct solve: keeps
    # the cascade fuzz harness runnable (committed 24-case run in
    # benchmarks/results/fuzz_cascade_sim_cpu.jsonl)
    from benchmarks import fuzz_cascade

    rc = fuzz_cascade.main(1, 3001, 4)
    recs = _records(capsys)
    assert len(recs) == 2  # 1 case + summary
    assert rc == 0 and recs[-1]["violations"] == 0
    case = recs[0]
    assert set(case["topologies"]) == {"tree", "star"}
    for t in case["topologies"].values():
        assert t["converged"] and t["n_sv"] > 0
    assert case["sv_jaccard"] >= 0.9
    assert case["direct_status"] == "CONVERGED"


def test_sweep_p_tree_skips_non_power_of_two(capsys):
    from benchmarks import sweep_p

    rc = sweep_p.main([
        "--platform", "native", "--n", "256", "--n-test", "64",
        "--d", "16", "--shards", "3", "--topologies", "tree",
        "--sv-capacity", "128", "--gamma", "0.0625",
    ])
    assert rc == 0
    assert _records(capsys) == []  # P=3 tree is skipped, nothing emitted


def test_telemetry_overhead_smoke_schema(capsys):
    # the convergence-telemetry cost harness (ISSUE 5): schema + the
    # load-independent hard gate — the telemetry arm is BIT-identical to
    # the off arm. The <= 3% overhead floor is asserted only on the
    # committed full-size run (a smoke-shape CPU timing is pure noise)
    from benchmarks import telemetry_overhead

    rc = telemetry_overhead.main(["--smoke"])
    assert rc == 0
    recs = _records(capsys)
    assert len(recs) == 1
    r = recs[0]
    assert r["bench"] == "telemetry_overhead"
    assert r["workload"]["synthetic"] is True
    assert r["bit_identical"] is True
    assert r["t_off_s"] > 0 and r["t_on_s"] > 0
    assert r["rounds_recorded"] >= 1
    assert r["status"] == "CONVERGED"
    assert r["final_gap"] is not None and r["final_gap"] <= 2e-5 * 1.001
    assert r["violations"] == []
    # the committed CPU record carries the same schema AND passes the
    # acceptance gates this PR claims (<= 3% overhead, bit identity)
    import json as _json
    import os as _os

    path = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "benchmarks", "results",
        "telemetry_overhead_cpu.jsonl")
    committed = [_json.loads(line) for line in open(path)]
    # provenance (the performance-observatory backend stamp) is newer
    # than the committed full-scale artifact
    assert committed and set(r) - {"provenance"} <= set(committed[0])
    full = committed[-1]
    assert full["smoke"] is False
    assert full["bit_identical"] is True
    assert full["overhead_frac"] <= full["gate_frac"] == 0.03
    assert full["violations"] == []


def test_ingest_throughput_smoke_schema(capsys):
    from benchmarks import ingest_throughput

    rc = ingest_throughput.main(["--smoke"])
    recs = _records(capsys)
    assert rc == 0
    assert len(recs) == 1
    r = recs[0]
    assert r["bench"] == "ingest_throughput"
    assert r["workload"]["synthetic"] is True
    assert r["n_shards"] >= 1
    assert r["ingest_s"] > 0 and r["ingest_rows_per_s"] > 0
    assert r["cold_batches_per_s"] > 0 and r["prefetch_batches_per_s"] > 0
    assert r["prefetch_speedup"] > 0
    # the residency bound is part of the committed evidence
    assert r["max_live_shards"] <= r["prefetch_depth"] + 1
    assert r["violations"] == []
    # the committed CPU curve carries the same schema
    import json as _json
    import os as _os

    path = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "benchmarks", "results",
        "ingest_throughput_cpu.jsonl")
    committed = [_json.loads(line) for line in open(path)]
    # provenance (the performance-observatory backend stamp) is newer
    # than the committed full-scale artifact
    assert committed and set(r) - {"provenance"} <= set(committed[0])
