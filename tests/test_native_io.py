"""Native C++ CSV loader vs the reference-faithful Python reader (C1).

The native path is optional (scripts/build_native.sh); tests that need the
shared library build it on demand and skip if no compiler is available.
"""

import os
import subprocess

import numpy as np
import pytest

from tpusvm.data import read_csv, write_csv
from tpusvm.data.native_io import native_available, read_csv_fast

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def native_lib():
    if not native_available():
        try:
            subprocess.run(
                [os.path.join(REPO, "scripts", "build_native.sh")],
                check=True, capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            pytest.skip(f"cannot build native library: {e}")
        # force re-probe after the build
        import tpusvm.data.native_io as nio

        nio._lib_checked = False
        if not native_available():
            pytest.skip("native library unavailable after build")
    return True


@pytest.fixture()
def csv_file(tmp_path):
    rng = np.random.default_rng(42)
    X = rng.standard_normal((300, 17))
    labels = rng.integers(0, 10, 300)
    path = str(tmp_path / "d.csv")
    d = X.shape[1]
    header = ",".join([f"c{i}" for i in range(d)] + ["label"])
    with open(path, "w") as f:
        f.write(header + "\n")
        for row, lab in zip(X, labels):
            f.write(",".join(f"{v:.17g}" for v in row) + f",{lab}\n")
    return path, X, labels


def test_native_matches_python(native_lib, csv_file):
    path, X, labels = csv_file
    Xp, Yp = read_csv(path)
    Xn, Yn = read_csv_fast(path)
    np.testing.assert_allclose(Xn, Xp, rtol=0, atol=0)
    np.testing.assert_array_equal(Yn, Yp)
    assert set(np.unique(Yn)) <= {1, -1}


def test_native_n_limit(native_lib, csv_file):
    path, X, _ = csv_file
    Xn, Yn = read_csv_fast(path, n_limit=37)
    assert Xn.shape == (37, 17) and len(Yn) == 37
    np.testing.assert_allclose(Xn, X[:37], rtol=1e-15)


def test_native_raw_labels(native_lib, csv_file):
    path, _, labels = csv_file
    Xn, Yn = read_csv_fast(path, binary_labels=False)
    np.testing.assert_array_equal(Yn, labels)


def test_native_missing_file_raises(native_lib, tmp_path):
    with pytest.raises(OSError):
        read_csv_fast(str(tmp_path / "nope.csv"))


def test_native_empty_body(native_lib, tmp_path):
    path = str(tmp_path / "empty.csv")
    with open(path, "w") as f:
        f.write("a,b,label\n")
    X, Y = read_csv_fast(path)
    assert X.shape == (0, 2) and len(Y) == 0


def test_native_skips_short_rows(native_lib, tmp_path):
    # <2-field lines skip without parsing, like Python's len(split(',')) < 2:
    # empty line, bare number, whitespace-only line, bare garbage
    path = str(tmp_path / "short.csv")
    with open(path, "w") as f:
        f.write("a,b,label\n1.5,2.5,1\n\n7\n \nx\n3.5,4.5,0\n")
    X, Y = read_csv_fast(path)
    Xp, Yp = read_csv(path)
    np.testing.assert_allclose(X, Xp)
    np.testing.assert_array_equal(Y, Yp)
    assert len(Y) == 2 and Y.tolist() == [1, -1]


def test_native_n_limit_stops_before_malformed(native_lib, tmp_path):
    # the Python reader breaks at the cap, so malformed rows past it never
    # raise; the fast path must do the same
    path = str(tmp_path / "cap.csv")
    with open(path, "w") as f:
        f.write("a,b,label\n1.0,2.0,1\n3.0,4.0,0\noops,bad,1\n")
    Xn, Yn = read_csv_fast(path, n_limit=2)
    Xp, Yp = read_csv(path, n_limit=2)
    np.testing.assert_allclose(Xn, Xp)
    np.testing.assert_array_equal(Yn, Yp)
    assert len(Yn) == 2


def test_native_n_limit_zero_matches_python(native_lib, csv_file):
    path, _, _ = csv_file
    Xn, Yn = read_csv_fast(path, n_limit=0)
    Xp, Yp = read_csv(path, n_limit=0)
    assert len(Yn) == len(Yp) == 0
    assert Xn.shape == Xp.shape == (0, 17)


def test_native_rejects_hex_floats(native_lib, tmp_path):
    # strtod parses C hex floats; Python's float() raises — parity demands
    # the fast path raise too
    path = str(tmp_path / "hex.csv")
    with open(path, "w") as f:
        f.write("a,b,label\n0x10,2.0,1\n")
    with pytest.raises(ValueError):
        read_csv_fast(path)
    with pytest.raises(ValueError):
        read_csv(path)


def test_python_raw_labels(csv_file):
    path, _, labels = csv_file
    X, Y = read_csv(path, binary=False)
    np.testing.assert_array_equal(Y, labels)
    X2, Y2 = read_csv(path, n_limit=10, binary=False)
    assert len(Y2) == 10


def test_write_read_roundtrip_via_fast(tmp_path, native_lib):
    rng = np.random.default_rng(7)
    X = rng.random((50, 5))
    Y = rng.choice([1, -1], 50).astype(np.int32)
    path = str(tmp_path / "rt.csv")
    write_csv(path, X, Y)
    Xr, Yr = read_csv_fast(path)
    np.testing.assert_allclose(Xr, X, atol=1e-12)
    np.testing.assert_array_equal(Yr, Y)


def test_native_malformed_raises(native_lib, tmp_path):
    # unparsable field: both readers raise ValueError
    bad = str(tmp_path / "bad.csv")
    with open(bad, "w") as f:
        f.write("a,b,label\n1.0,oops,1\n")
    with pytest.raises(ValueError):
        read_csv_fast(bad)
    with pytest.raises(ValueError):
        read_csv(bad)
    # ragged row (field count != header): native rejects loudly
    ragged = str(tmp_path / "ragged.csv")
    with open(ragged, "w") as f:
        f.write("a,b,c,label\n1.0,2.0,3.0,1\n1.0,2.0,1\n")
    with pytest.raises(ValueError):
        read_csv_fast(ragged)
    # whitespace-only trailing field: strtod's leading-whitespace skip must
    # not cross the newline and merge the next line's first number into this
    # row (the Python reader raises on float(' '))
    ws = str(tmp_path / "ws.csv")
    with open(ws, "w") as f:
        f.write("a,b,label\n1.0,2.0, \n3.0,4.0,1\n")
    with pytest.raises(ValueError):
        read_csv_fast(ws)
    with pytest.raises(ValueError):
        read_csv(ws)
    # trailing garbage after a number raises, like Python float("1.0x")
    junk = str(tmp_path / "junk.csv")
    with open(junk, "w") as f:
        f.write("a,b,label\n1.0x,2.0,1\n")
    with pytest.raises(ValueError):
        read_csv_fast(junk)
    with pytest.raises(ValueError):
        read_csv(junk)


# ------------------------------------------------- checked-in fixture parity
FIXTURE = os.path.join(REPO, "tests", "fixtures", "native_parity.csv")


def test_fixture_native_python_parity(native_lib):
    # the committed fixture exercises denormals/extremes/exponent notation,
    # a skipped bare-number row and a skipped blank line; native and Python
    # readers must agree to the BYTE on X and Y
    Xp, Yp = read_csv(FIXTURE)
    Xn, Yn = read_csv_fast(FIXTURE)
    assert Xp.shape == (11, 5)  # 13 data lines - 2 short rows skipped
    assert Xn.tobytes() == Xp.tobytes()
    assert Yn.tobytes() == Yp.tobytes()
    assert set(np.unique(Yp)) == {1, -1}


def test_fixture_parity_n_limit_and_raw(native_lib):
    for n_limit in (0, 3, 5, 100):
        Xn, Yn = read_csv_fast(FIXTURE, n_limit=n_limit)
        Xp, Yp = read_csv(FIXTURE, n_limit=n_limit)
        assert Xn.tobytes() == Xp.tobytes()
        assert Yn.tobytes() == Yp.tobytes()
    Xn, Yn = read_csv_fast(FIXTURE, binary_labels=False)
    Xp, Yp = read_csv(FIXTURE, binary=False)
    assert Yn.tobytes() == Yp.tobytes()
    assert Yn.tolist() == [1, 0, 7, 1, 2, -1, 1, 10, 1, 3, 0]


def test_fixture_parity_positive_label(native_lib):
    # non-default positive class: the native path reads RAW labels and
    # remaps on the host — must match the pure-Python mapping exactly
    for k in (0, 7, -1, 99):
        Xn, Yn = read_csv_fast(FIXTURE, positive_label=k)
        Xp, Yp = read_csv(FIXTURE, positive_label=k)
        assert Xn.tobytes() == Xp.tobytes()
        assert Yn.tobytes() == Yp.tobytes()
        raw = read_csv(FIXTURE, binary=False)[1]
        np.testing.assert_array_equal(Yn, np.where(raw == k, 1, -1))


def test_positive_label_python_fallback(tmp_path):
    # pure-Python path (no native lib involvement): label != k -> -1
    p = str(tmp_path / "d.csv")
    with open(p, "w") as f:
        f.write("a,b,label\n1.0,2.0,7\n3.0,4.0,1\n5.0,6.0,0\n")
    X, Y = read_csv(p, positive_label=7)
    np.testing.assert_array_equal(Y, [1, -1, -1])
    X, Y = read_csv(p, positive_label=0)
    np.testing.assert_array_equal(Y, [-1, -1, 1])
