"""Routing tier: HRW placement, membership, health admission, rollout
skew, the forwarding proxy, and the serve-side replica identity.

The contracts under test (ISSUE 17 acceptance):
  * HRW placement properties — LEAVE re-maps only the leaver's keys,
    JOIN steals roughly 1/N of keys and nothing else, and the placement
    table for one (keys, replicas, k, seed) is byte-identical across
    processes and input container types;
  * ReplicaSet membership — torn-proof immutable views, version ticks
    only on real changes, listener sees every view in flip order;
  * health state machine — ok/degraded (breaker OR burning SLO budget)/
    draining/down with the down_after grace window, burn-aware
    admission ordering (placed tier first, ok before degraded,
    draining/down excluded);
  * rollout skew — the window predicate (held iff skew > window),
    unknown generations reported not guessed, staggered_rollout holds
    instead of fanning out a split, and the per-replica swap POST is
    never retried;
  * the proxy — failover on connection failure/replica 503 under the
    shared Retry machinery, 429 backpressure passed through WITHOUT
    failover, NO_REPLICA/ALL_DOWN statuses, counters;
  * serve replicas — stable persisted replica_id + uptime_s in
    /healthz, the actual bound address recorded in serve_state.json
    (the --port 0 contract), identity surviving a kill/revive.
"""

import json
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm import faults
from tpusvm.config import SVMConfig
from tpusvm.data import rings
from tpusvm.models import BinarySVC
from tpusvm.obs.registry import MetricsRegistry
from tpusvm.router import (
    HealthPoller,
    ReplicaSet,
    Router,
    RouterConfig,
    SkewReport,
    check_skew,
    generation_vector,
    hrw_score,
    place,
    placement_table,
    skew_of,
    staggered_rollout,
    table_bytes,
)
from tpusvm.serve import ServeConfig, Server
from tpusvm.status import RouterStatus

URLS = tuple(f"http://10.0.0.{i}:8400" for i in range(1, 7))
KEYS = [f"model-{i}" for i in range(200)]


# ------------------------------------------------------------- placement
def test_hrw_score_is_seeded_and_stable():
    assert hrw_score("m", "a") == hrw_score("m", "a")
    assert hrw_score("m", "a") != hrw_score("m", "a", seed=1)
    # length mixing: ("ab","c") and ("a","bc") must not collide
    assert hrw_score("c", "ab") != hrw_score("bc", "a")


def test_place_is_deterministic_top_k():
    got = place("m", URLS, k=3, seed=7)
    assert len(got) == 3 and len(set(got)) == 3
    assert got == place("m", list(URLS), k=3, seed=7)
    ranked = sorted(URLS, key=lambda r: (-hrw_score("m", r, 7), r))
    assert got == tuple(ranked[:3])


def test_place_k_below_one_raises():
    with pytest.raises(ValueError, match="replication factor"):
        place("m", URLS, k=0)
    with pytest.raises(ValueError, match="replication factor"):
        ReplicaSet(URLS, k=0)
    with pytest.raises(ValueError, match="replication"):
        RouterConfig(replicas=URLS, replication=0)


def test_place_fewer_replicas_than_k_hosts_everywhere():
    assert set(place("m", URLS[:2], k=5)) == set(URLS[:2])


def test_table_bytes_reproducible_per_seed():
    a = table_bytes(placement_table(KEYS, URLS, k=2, seed=3))
    b = table_bytes(placement_table(tuple(KEYS), list(URLS), k=2, seed=3))
    assert a == b
    assert a != table_bytes(placement_table(KEYS, URLS, k=2, seed=4))


def test_leave_moves_only_the_leavers_keys():
    before = placement_table(KEYS, URLS, k=2, seed=5)
    leaver = URLS[2]
    after = placement_table(KEYS, [u for u in URLS if u != leaver],
                            k=2, seed=5)
    for key in KEYS:
        if leaver in before[key]:
            continue  # this key's placement may change (its slot refills)
        assert after[key] == before[key], key


def test_join_moves_at_most_its_fair_share():
    n = len(URLS)
    before = placement_table(KEYS, URLS, k=1, seed=5)
    joined = URLS + ("http://10.0.0.99:8400",)
    after = placement_table(KEYS, joined, k=1, seed=5)
    moved = [k for k in KEYS if after[k] != before[k]]
    # every moved key moved TO the joiner (nothing reshuffles elsewhere)
    assert all(after[k] == (joined[-1],) for k in moved)
    # expectation is len/ (n+1); allow a generous statistical margin
    assert len(moved) <= 2.5 * len(KEYS) / (n + 1)


# ------------------------------------------------------------ membership
def test_replica_set_views_and_versions():
    rs = ReplicaSet(("b", "a", "a"), k=1, seed=0)
    assert rs.replicas() == ("a", "b")       # sorted, deduped
    assert rs.version == 1
    assert rs.join("c") and rs.version == 2
    assert not rs.join("c") and rs.version == 2   # dedup: no tick
    assert rs.leave("a") and rs.version == 3
    assert not rs.leave("zz") and rs.version == 3
    assert rs.replicas() == ("b", "c")
    assert rs.placement("m") in (("b",), ("c",))
    assert ReplicaSet((), k=1).placement("m") == ()


def test_replica_set_listener_sees_every_view_in_order():
    log = []
    rs = ReplicaSet(("a",), k=1,
                    listener=lambda v: log.append((v.version, v.replicas)))
    rs.join("b")
    rs.join("b")   # no-op: not logged
    rs.leave("a")
    assert log == [(1, ("a",)), (2, ("a", "b")), (3, ("b",))]
    assert (rs.version, rs.replicas()) == log[-1]


# ---------------------------------------------------------------- health
def _payload(status="ok", gen=1, burning=(), breakers=None,
             replica_id="r-x", uptime=12.5):
    return {
        "status": status,
        "replica_id": replica_id,
        "uptime_s": uptime,
        "models": breakers or {"m": "closed"},
        "swap": {"m": {"generation": gen}},
        "slo": {name: {"burning": True} for name in burning},
    }


def _poller(fetches, **kw):
    """Poller over stub replicas; `fetches[url]` is a callable or dict."""

    def fetch(url, timeout_s=0.0):
        f = fetches[url]
        out = f() if callable(f) else f
        if isinstance(out, Exception):
            raise out
        return out

    kw.setdefault("down_after", 2)
    return HealthPoller(tuple(fetches), interval_s=0.05, fetch=fetch,
                        registry=MetricsRegistry(), **kw)


def test_health_states_ok_degraded_draining():
    p = _poller({
        "a": _payload(),
        "b": _payload(status="degraded"),
        "c": _payload(burning=("m",)),        # burn-aware: ok + burning
        "d": _payload(status="draining"),
    })
    assert p.poll_once() == {"a": "ok", "b": "degraded",
                             "c": "degraded", "d": "draining"}
    rec = p.snapshot()["a"]
    assert rec.replica_id == "r-x" and rec.uptime_s == 12.5
    assert rec.generations == {"m": 1} and rec.failures == 0
    assert p.snapshot()["c"].burning == ("m",)


def test_health_down_after_grace_window():
    state = {"fail": False}

    def flaky():
        if state["fail"]:
            return ConnectionRefusedError("refused")
        return _payload()

    p = _poller({"a": flaky}, down_after=2)
    assert p.poll_once() == {"a": "ok"}
    state["fail"] = True
    # one missed poll keeps the previous state (transient blip)
    assert p.poll_once() == {"a": "ok"}
    assert p.snapshot()["a"].failures == 1
    assert p.poll_once() == {"a": "down"}      # streak hits down_after
    state["fail"] = False
    assert p.poll_once() == {"a": "ok"}        # recovery resets
    assert p.snapshot()["a"].failures == 0


def test_health_never_polled_is_down_immediately():
    p = _poller({"a": ConnectionRefusedError("refused")}, down_after=3)
    assert p.poll_once() == {"a": "down"}      # polls == 0: no grace


def test_health_poller_validates_knobs():
    with pytest.raises(ValueError, match="interval_s"):
        HealthPoller(("a",), interval_s=0.0, registry=MetricsRegistry())
    with pytest.raises(ValueError, match="down_after"):
        HealthPoller(("a",), down_after=0, registry=MetricsRegistry())


def test_admissible_tiers_and_exclusions():
    p = _poller({
        "a": _payload(),                       # ok
        "b": _payload(burning=("m",)),         # degraded
        "c": _payload(status="draining"),      # excluded
        "d": ConnectionRefusedError("x"),      # down: excluded
        "e": _payload(),                       # ok (fallback tier)
    })
    p.poll_once()
    # placed tier first, ok before degraded inside each tier
    assert p.admissible(["b", "a", "c", "d"],
                        fallback=["e", "b"]) == ["a", "b", "e"]
    # a replica the poller has never seen is excluded outright
    assert p.admissible(["zz"], fallback=[]) == []


# ------------------------------------------------------------------ skew
class _Rec:
    def __init__(self, state="ok", polls=1, gens=None):
        self.state = state
        self.polls = polls
        self.generations = gens or {}


def test_generation_vector_and_skew():
    snap = {
        "a": _Rec(gens={"m": 3}),
        "b": _Rec(gens={"m": 5}),
        "c": _Rec(state="down", gens={"m": 9}),   # down: unknown
        "d": _Rec(polls=0),                       # never polled: unknown
        "e": _Rec(gens={}),                       # no such model: unknown
    }
    vec = generation_vector(snap, "m")
    assert vec == {"a": 3, "b": 5, "c": None, "d": None, "e": None}
    assert skew_of(vec) == 2
    assert skew_of({"a": None, "b": 4}) == 0    # < 2 known gens
    rep = check_skew(snap, "m", window=1)
    assert isinstance(rep, SkewReport)
    assert rep.held and rep.skew == 2 and rep.unknown == ("c", "d", "e")
    assert rep.laggards == ("a",)
    assert check_skew(snap, "m", window=2).held is False  # boundary
    j = rep.to_json()
    assert j["held"] and j["laggards"] == ["a"] and j["skew"] == 2


def test_check_skew_window_validation():
    with pytest.raises(ValueError, match="window"):
        check_skew({}, "m", window=-1)


def test_staggered_rollout_swaps_each_replica_once():
    gens = {"a": 1, "b": 1, "c": 1}
    p = _poller({u: (lambda u=u: _payload(gen=gens[u])) for u in gens})
    posts = []

    def post(url, obj, timeout_s=0.0):
        posts.append((url, obj["name"]))
        base = url[:-len("/admin/swap")]
        gens[base] += 1
        return 200, {"swapped": True, "generation": gens[base]}

    out = staggered_rollout(p, "m", "/tmp/v2.npz", window=1, post=post)
    assert out["status"] == RouterStatus.OK.name
    assert out["swapped"] == ["a", "b", "c"] and not out["failed"]
    assert out["report"]["skew"] == 0 and not out["report"]["unknown"]
    # non-idempotent: exactly one POST per replica, in sorted order
    assert posts == [(u + "/admin/swap", "m") for u in ("a", "b", "c")]


def test_staggered_rollout_holds_on_skew_and_posts_nothing():
    p = _poller({"a": _payload(gen=1), "b": _payload(gen=4)})
    posts = []

    def post(url, obj, timeout_s=0.0):
        posts.append(url)
        return 200, {"swapped": True, "generation": 5}

    out = staggered_rollout(p, "m", "/x.npz", window=1, post=post)
    assert out["status"] == RouterStatus.SKEW_HOLD.name
    assert posts == [] and out["swapped"] == []
    assert out["report"]["laggards"] == ["a"]


def test_staggered_rollout_skips_down_and_records_409():
    gens = {"a": 1, "b": 1}
    fetches = {
        "a": lambda: _payload(gen=gens["a"]),
        "b": lambda: _payload(gen=gens["b"]),
        "c": ConnectionRefusedError("dead"),
    }
    p = _poller(fetches, down_after=1)

    def post(url, obj, timeout_s=0.0):
        base = url[:-len("/admin/swap")]
        if base == "b":
            return 409, {"error": "stage failed, rolled back"}
        gens[base] += 1
        return 200, {"swapped": True, "generation": gens[base]}

    out = staggered_rollout(p, "m", "/x.npz", window=1, post=post)
    assert out["swapped"] == ["a"] and out["skipped"] == ["c"]
    assert "b" in out["failed"] and "409" in out["failed"]["b"]
    # a+1 vs b at gen 1 is skew 1: inside the window, rollout completes
    assert out["status"] == RouterStatus.OK.name


# ----------------------------------------------------------------- proxy
def _router(fetches, transport, **cfg_kw):
    cfg_kw.setdefault("replicas", tuple(sorted(fetches)))
    cfg_kw.setdefault("replication", 2)
    cfg_kw.setdefault("poll_interval_s", 10.0)

    def fetch(url, timeout_s=0.0):
        f = fetches[url]
        out = f() if callable(f) else f
        if isinstance(out, Exception):
            raise out
        return out

    r = Router(RouterConfig(**cfg_kw), transport=transport, fetch=fetch,
               registry=MetricsRegistry(), log_fn=None)
    r.poller.poll_once()
    return r


def _metric(router, name):
    return sum(m["value"] for m in router._registry.snapshot()["metrics"]
               if m["name"] == name)


def test_forward_success_passes_through():
    calls = []

    def transport(url, body, timeout_s):
        calls.append(url)
        return 200, b'{"scores": [1.5]}', None

    r = _router({"http://a": _payload(), "http://b": _payload()},
                transport)
    code, data, ra = r.forward("m", b'{"instances": [[0, 0]]}')
    assert (code, ra) == (200, None)
    assert json.loads(data)["scores"] == [1.5]
    assert calls == [r.replica_set.placement("m")[0]
                     + "/v1/models/m:predict"]
    assert _metric(r, "router.requests") == 1
    assert _metric(r, "router.failovers") == 0
    r.poller.stop()


def test_forward_fails_over_on_connection_failure_and_503():
    for failure in (faults.TransientIOError("refused"),
                    (503, b'{"error": "half-dead"}', None)):
        first = []

        def transport(url, body, timeout_s, failure=failure, first=first):
            if not first:
                first.append(url)
                if isinstance(failure, Exception):
                    raise failure
                return failure
            return 200, b'{"scores": [2.0]}', None

        r = _router({"http://a": _payload(), "http://b": _payload()},
                    transport)
        code, data, _ = r.forward("m", b"{}")
        assert code == 200 and json.loads(data)["scores"] == [2.0]
        assert _metric(r, "router.failovers") == 1
        assert _metric(r, "router.retries") == 1
        r.poller.stop()


def test_forward_429_backpressure_never_fails_over():
    calls = []

    def transport(url, body, timeout_s):
        calls.append(url)
        return 429, b'{"error": "OVERLOADED"}', None

    r = _router({"http://a": _payload(), "http://b": _payload()},
                transport)
    code, _data, ra = r.forward("m", b"{}")
    assert code == 429
    assert ra == "1"            # honest backpressure default hint
    assert len(calls) == 1      # no failover: load is not bounced
    assert _metric(r, "router.failovers") == 0
    r.poller.stop()


def test_forward_all_down_and_no_replica():
    def transport(url, body, timeout_s):
        raise faults.TransientIOError("refused")

    r = _router({"http://a": _payload(), "http://b": _payload()},
                transport)
    code, data, _ = r.forward("m", b"{}")
    assert code == 503
    assert json.loads(data)["router"] == RouterStatus.ALL_DOWN.name
    assert _metric(r, "router.failovers") == 1
    r.poller.stop()

    r2 = _router({"http://a": ConnectionRefusedError("dead")}, transport,
                 down_after=1)
    code, data, _ = r2.forward("m", b"{}")
    assert code == 503
    assert json.loads(data)["router"] == RouterStatus.NO_REPLICA.name
    assert _metric(r2, "router.no_replica") == 1
    r2.poller.stop()


def test_forward_attempts_pass_the_fault_point():
    def transport(url, body, timeout_s):
        return 200, b"{}", None

    r = _router({"http://a": _payload(), "http://b": _payload()},
                transport)
    plan = faults.FaultPlan([faults.FaultRule(
        point="router.forward", kind="transient", p=1.0, max_hits=1)])
    with faults.active(plan):
        code, _, _ = r.forward("m", b"{}")
    assert code == 200            # injected transient absorbed by failover
    assert plan.hits("router.forward") == 2
    assert _metric(r, "router.retries") == 1
    assert _metric(r, "router.failovers") == 1
    r.poller.stop()


def test_router_status_and_health_rollup():
    assert [s.name for s in RouterStatus] == [
        "OK", "NO_REPLICA", "ALL_DOWN", "SKEW_HOLD"]

    def transport(url, body, timeout_s):
        return 200, b"{}", None

    r = _router({}, transport, replicas=())
    assert r.status_code() == RouterStatus.NO_REPLICA
    assert r.health()["status"] == "down"
    r.poller.stop()

    r = _router({"http://a": ConnectionRefusedError("dead")}, transport,
                down_after=1)
    assert r.status_code() == RouterStatus.ALL_DOWN
    r.poller.stop()

    r = _router({"http://a": _payload()}, transport)
    assert r.status_code() == RouterStatus.OK
    with r._lock:
        r._holds["m"] = {"skew": 2}
    assert r.status_code() == RouterStatus.SKEW_HOLD
    h = r.health()
    assert h["status"] == "degraded" and h["holds"]["m"]["skew"] == 2
    assert h["placement"]["replicas"] == ["http://a"]
    r.poller.stop()


def test_router_rollout_sets_and_clears_hold(monkeypatch):
    # the hold state machine, with the rollout driver itself stubbed:
    # SKEW_HOLD installs the report on /healthz, a later OK clears it
    import tpusvm.router.proxy as proxy_mod

    outcomes = [
        {"status": RouterStatus.SKEW_HOLD.name, "swapped": [],
         "skipped": [], "failed": {}, "report": {"skew": 2}},
        {"status": RouterStatus.OK.name, "swapped": ["http://a"],
         "skipped": [], "failed": {}, "report": {"skew": 0}},
    ]
    seen = []

    def stub(poller, model, path, window=1, **kw):
        seen.append((model, path, window))
        return outcomes[len(seen) - 1]

    monkeypatch.setattr(proxy_mod, "staggered_rollout", stub)
    r = _router({"http://a": _payload()}, lambda *a: (200, b"{}", None),
                skew_window=2)
    out = r.rollout("m", "/x.npz")
    assert out["status"] == RouterStatus.SKEW_HOLD.name
    assert r.holds() == {"m": {"skew": 2}}
    assert r.status_code() == RouterStatus.SKEW_HOLD
    out = r.rollout("m", "/x.npz", window=1)
    assert out["status"] == RouterStatus.OK.name
    assert not r.holds() and r.status_code() == RouterStatus.OK
    # config skew_window is the default; an explicit window overrides
    assert seen == [("m", "/x.npz", 2), ("m", "/x.npz", 1)]
    r.poller.stop()


# -------------------------------------------- HTTP front door (end to end)
@pytest.fixture(scope="module")
def served_fleet():
    """Two real in-process serve replicas + a router front door."""
    from tpusvm.serve.http import make_http_server, start_http_thread

    X, Y = rings(n=240, seed=2)
    model = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float32).fit(X, Y)
    servers, urls = [], []
    for _ in range(2):
        srv = Server(ServeConfig(max_batch=8), dtype=jnp.float32)
        srv.add_model("m", model)
        srv.warmup()
        httpd = make_http_server(srv, port=0)
        srv.attach_http(httpd, start_http_thread(httpd))
        host, port = httpd.server_address[:2]
        servers.append(srv)
        urls.append(f"http://{host}:{port}")
    router = Router(RouterConfig(replicas=tuple(urls), replication=2,
                                 seed=3, poll_interval_s=10.0),
                    registry=MetricsRegistry(), log_fn=None)
    router.poller.poll_once()
    from tpusvm.router import make_router_http
    httpd = make_router_http(router, port=0)
    router.attach_http(httpd,
                       threading.Thread(target=httpd.serve_forever,
                                        daemon=True))
    router._http_thread.start()
    host, port = httpd.server_address[:2]
    yield servers, router, f"http://{host}:{port}", model
    router.close()
    for srv in servers:
        srv.close()


def _get(url):
    import urllib.request
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def _post(url, obj):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_router_http_predict_and_introspection(served_fleet):
    servers, router, base, model = served_fleet
    Xq, _ = rings(n=4, seed=3)
    ref = np.asarray(model.decision_function(Xq)).ravel()
    code, out = _post(base + "/v1/models/m:predict",
                      {"instances": np.asarray(Xq, float).tolist()})
    assert code == 200
    got = np.asarray(out["scores"], float).ravel()
    assert np.array_equal(got.astype(np.float32),
                          ref.astype(np.float32))

    code, h = _get(base + "/healthz")
    assert code == 200 and h["router"] == RouterStatus.OK.name
    assert set(h["replicas"].values()) == {"ok"}

    code, detail = _get(base + "/v1/replicas")
    assert code == 200 and len(detail) == 2
    for rec in detail.values():
        assert rec["state"] == "ok" and rec["replica_id"]
        assert rec["uptime_s"] >= 0 and rec["generations"] == {"m": 1}

    import urllib.request
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert "tpusvm_router_requests_total" in text
    assert "tpusvm_router_forwards_total" in text

    code, err = _post(base + "/admin/rollout", {"name": "m"})
    assert code == 400 and "path" in err["error"]


def test_router_http_join_leave(served_fleet):
    _servers, router, base, _model = served_fleet
    v0 = router.replica_set.version
    code, out = _post(base + "/admin/join", {"url": "http://10.9.9.9:1"})
    assert code == 200 and out["changed"] and out["version"] == v0 + 1
    code, out = _post(base + "/admin/leave", {"url": "http://10.9.9.9:1"})
    assert code == 200 and out["changed"] and out["version"] == v0 + 2
    assert "http://10.9.9.9:1" not in router.replica_set.replicas()


# ------------------------------------------------- serve replica identity
def test_serve_health_reports_replica_id_and_uptime(served_fleet):
    servers, _router, _base, _model = served_fleet
    h = servers[0].health()
    assert h["replica_id"].startswith("r-") and len(h["replica_id"]) == 10
    assert h["uptime_s"] >= 0
    # ids are per-replica stable and distinct across the fleet
    assert servers[0].health()["replica_id"] == h["replica_id"]
    assert servers[1].health()["replica_id"] != h["replica_id"]


def test_serve_state_records_bound_address_and_identity(tmp_path):
    X, Y = rings(n=240, seed=2)
    model = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float32).fit(X, Y)
    mp = str(tmp_path / "m.npz")
    model.save(mp)
    state = str(tmp_path / "serve_state.json")
    with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
        srv.enable_state(state)
        srv.load_model("m", mp)
        srv.set_bound_address("127.0.0.1", 45678)
        assert srv.bound_address == "127.0.0.1:45678"
        first_id = srv.replica_id
    persisted = json.loads(open(state).read())
    assert persisted["address"] == "127.0.0.1:45678"
    assert persisted["replica_id"] == first_id
    # the revive: a fresh process adopts the persisted identity
    with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv2:
        restored = srv2.restore_state(state)
        assert isinstance(restored, dict)
        assert srv2.replica_id == first_id


def test_serve_port_zero_binds_ephemeral(tmp_path):
    from tpusvm.serve.http import make_http_server, start_http_thread
    X, Y = rings(n=240, seed=2)
    model = BinarySVC(SVMConfig(C=10.0, gamma=10.0),
                      dtype=jnp.float32).fit(X, Y)
    state = str(tmp_path / "serve_state.json")
    with Server(ServeConfig(max_batch=8), dtype=jnp.float32) as srv:
        srv.enable_state(state)
        srv.add_model("m", model)
        httpd = make_http_server(srv, port=0)
        srv.attach_http(httpd, start_http_thread(httpd))
        host, port = httpd.server_address[:2]
        assert port != 0
        srv.set_bound_address(host, port)
    persisted = json.loads(open(state).read())
    assert persisted["address"] == f"{host}:{port}"


# ----------------------------------------------------------- wiring pins
def test_fault_point_and_chaos_plan_cover_the_router():
    assert "router.forward" in faults.POINTS
    import os
    plan = json.loads(open(os.path.join(
        os.path.dirname(__file__), "fixtures",
        "chaos_plan.json")).read())
    kinds = sorted(r["kind"] for r in plan["rules"]
                   if r["point"] == "router.forward")
    assert kinds == ["latency", "transient"]


def test_ci_runs_the_router_gates():
    import os
    ci = open(os.path.join(os.path.dirname(__file__), "..", ".github",
                           "workflows", "ci.yml")).read()
    assert "router chaos smoke" in ci
    assert "router-chaos-smoke" in ci
    assert "router_fanout" in ci


def test_conc_stress_registers_the_router_suite():
    from tpusvm.analysis.conc import stress
    assert "router" in stress.SUITES
    assert "router" in stress.REAL_SUITES
    assert "router.flip" in stress.SUITE_SITES["router"]
    # a short real run: torn-view or version-skip violations raise
    stress.stress_router(seed=1, iters=40, threads=3)


def test_benchdiff_schema_covers_router_fanout():
    from tpusvm.obs.benchdiff import KEY_FIELDS, SCHEMA_RULES
    assert "replicas" in KEY_FIELDS
    rules = {r.metric: r for r in SCHEMA_RULES["router_fanout"]}
    assert rules["lost_responses"].direction == "=="
    assert rules["failover_ok"].direction == "=="
    assert rules["qps"].timing and rules["p99_ms"].timing
