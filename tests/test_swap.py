"""Resilient continuous serving: atomic hot-swap, persisted state,
crash-safe refresh, watch loop, classified load failures.

The contracts under test (ISSUE 14 acceptance):
  * swap-under-load parity: client threads stream requests while swaps
    flip between two models with known-distinct outputs — every scored
    response bitwise-matches exactly ONE of the two generations (no
    torn entry/cache pair), for exact AND approximate (rff) entries;
  * a failed stage (corrupt .npz, probe mismatch, injected kill) rolls
    back: the old generation keeps serving, healthz degrades, a later
    clean swap recovers;
  * kill-at-every-checkpoint refresh: a `refresh_fit` killed at any
    solver checkpoint and resumed is BIT-IDENTICAL (alpha bytes, SV
    ids, b) to an uninterrupted refresh, and the swapped-in model
    serves those exact bytes;
  * serve_state.json: atomic write, full-model-set restore with
    generation continuity, named errors for corrupt state;
  * --watch: new stems load, newer mtimes swap, failed artifacts are
    remembered (no hot-loop) until their mtime moves.
"""

import json
import os
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm import faults
from tpusvm.config import SVMConfig
from tpusvm.data import rings
from tpusvm.models import BinarySVC
from tpusvm.serve import ModelLoadError, ServeConfig, Server
from tpusvm.status import ServeStatus

CFG_A = SVMConfig(C=10.0, gamma=10.0)
CFG_B = SVMConfig(C=10.0, gamma=5.0)


@pytest.fixture(scope="module")
def two_models():
    Xa, Ya = rings(n=240, seed=2)
    Xb, Yb = rings(n=240, seed=9)
    A = BinarySVC(CFG_A, dtype=jnp.float64).fit(Xa, Ya)
    B = BinarySVC(CFG_B, dtype=jnp.float64).fit(Xb, Yb)
    return A, B


@pytest.fixture()
def model_paths(two_models, tmp_path):
    A, B = two_models
    pa, pb = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    A.save(pa)
    B.save(pb)
    return pa, pb


# ----------------------------------------------------------- atomic swap
def test_swap_flips_generation_and_scores(two_models):
    A, B = two_models
    Xq, _ = rings(n=16, seed=3)
    with Server(ServeConfig(max_batch=8), dtype=jnp.float64) as srv:
        srv.add_model("m", A)
        srv.warmup()
        refA, _ = srv.predict_direct("m", Xq)
        assert srv.registry.generation("m") == 1
        out = srv.swap("m", B)
        assert out["generation"] == 2
        assert out["latency_s"] > 0 and out["staleness_before_s"] >= 0
        refB, _ = srv.predict_direct("m", Xq)
        assert not np.array_equal(refA, refB)
        # bitwise the offline arithmetic of the NEW model
        assert np.array_equal(refB, np.asarray(B.decision_function(Xq)))
        snap = srv.metrics("m")
        assert snap["swaps"] == 1 and snap["swap_failures"] == 0
        h = srv.health()
        assert h["status"] == "ok"
        assert h["swap"]["m"]["generation"] == 2
        assert h["swap"]["m"]["last_swap"]["outcome"] == "ok"
        assert h["swap"]["m"]["staleness_s"] >= 0


def test_swap_under_load_no_torn_reads(two_models):
    """The acceptance-criteria core: concurrent clients + repeated swaps;
    every OK response bitwise-matches exactly one generation."""
    A, B = two_models
    Xq, _ = rings(n=32, seed=3)
    with Server(ServeConfig(max_batch=8), dtype=jnp.float64) as srv:
        srv.add_model("m", A)
        srv.warmup()
        refA, _ = srv.predict_direct("m", Xq)
        srv.swap("m", B)
        refB, _ = srv.predict_direct("m", Xq)
        srv.swap("m", A)
        assert not np.array_equal(refA, refB)

        stop = threading.Event()
        bad = []
        lock = threading.Lock()

        def client(t):
            i = t
            while not stop.is_set():
                r = srv.submit("m", Xq[i % 32], timeout_s=10.0)
                if not r.ok:
                    with lock:
                        bad.append(("status", ServeStatus(r.status).name))
                else:
                    s = np.asarray(r.scores)
                    if s != refA[i % 32] and s != refB[i % 32]:
                        with lock:
                            bad.append(("torn", i % 32, float(s)))
                i += 1

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for k in range(6):
            srv.swap("m", B if k % 2 == 0 else A)
        stop.set()
        for t in threads:
            t.join(10.0)
        assert not bad, bad[:10]
        assert srv.registry.generation("m") == 9  # 1 + 2 setup + 6 loop
        assert srv.metrics("m")["errors"] == 0


def test_swap_under_load_rff_entries():
    """The approximate-kernel serving path swaps atomically too: two rff
    models differing only in map seed have distinct fused executables
    and distinct scores — responses must match exactly one of them."""
    X, Y = rings(n=256, seed=5)
    ma = BinarySVC(SVMConfig(C=10.0, gamma=10.0, kernel="rff",
                             rff_dim=128, map_seed=0)).fit(X, Y)
    mb = BinarySVC(SVMConfig(C=10.0, gamma=10.0, kernel="rff",
                             rff_dim=128, map_seed=7)).fit(X, Y)
    Xq, _ = rings(n=16, seed=6)
    with Server(ServeConfig(max_batch=4)) as srv:
        srv.add_model("m", ma)
        srv.warmup()
        refA, _ = srv.predict_direct("m", Xq)
        srv.swap("m", mb)
        refB, _ = srv.predict_direct("m", Xq)
        srv.swap("m", ma)
        assert not np.array_equal(refA, refB)

        stop = threading.Event()
        bad = []
        lock = threading.Lock()

        def client():
            i = 0
            while not stop.is_set():
                r = srv.submit("m", Xq[i % 16], timeout_s=10.0)
                if r.ok:
                    s = np.asarray(r.scores)
                    if s != refA[i % 16] and s != refB[i % 16]:
                        with lock:
                            bad.append((i % 16, float(s)))
                i += 1

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        for k in range(4):
            srv.swap("m", mb if k % 2 == 0 else ma)
        stop.set()
        for t in threads:
            t.join(10.0)
        assert not bad, bad[:10]


def test_inflight_batch_finishes_on_its_generation(two_models):
    """A batch that started before the flip completes with the OLD
    bundle: slow the scoring path with an injected latency so the swap
    flips mid-batch, then check the response still matches a single
    generation (the _score one-bundle-read contract)."""
    A, B = two_models
    Xq, _ = rings(n=8, seed=4)
    plan = faults.FaultPlan([faults.FaultRule(
        point="serve.score", kind="latency", delay_ms=50.0, max_hits=4)],
        seed=0)
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        srv.add_model("m", A)
        srv.warmup()
        refA, _ = srv.predict_direct("m", Xq)
        srv.swap("m", B)
        refB, _ = srv.predict_direct("m", Xq)
        srv.swap("m", A)
        results = []
        with faults.active(plan):
            t = threading.Thread(target=lambda: results.extend(
                srv.submit_many("m", Xq, timeout_s=10.0)))
            t.start()
            srv.swap("m", B)  # flips while the slow batch is in flight
            t.join(15.0)
        for i, r in enumerate(results):
            assert r.ok, ServeStatus(r.status).name
            s = np.asarray(r.scores)
            assert s == refA[i] or s == refB[i]


# ------------------------------------------------- failure classification
def test_load_model_corrupt_npz_is_classified(model_paths, tmp_path):
    pa, _ = model_paths
    raw = open(pa, "rb").read()
    bad = str(tmp_path / "trunc.npz")
    with open(bad, "wb") as f:
        f.write(raw[: len(raw) // 2])
    with Server(ServeConfig(max_batch=4)) as srv:
        with pytest.raises(ModelLoadError) as ei:
            srv.load_model("x", bad)
        assert bad in str(ei.value)
        assert ei.value.status == ServeStatus.LOAD_FAILED
        with pytest.raises(ModelLoadError, match="missing.npz"):
            srv.load_model("y", str(tmp_path / "missing.npz"))
        # a non-model npz is named, not KeyError'd
        noise = str(tmp_path / "noise.npz")
        np.savez(noise, junk=np.arange(3))
        with pytest.raises(ModelLoadError, match="format_version"):
            srv.load_model("z", noise)
        assert srv.registry.names() == []  # nothing half-installed


def test_load_model_transient_io_is_retried(model_paths):
    pa, _ = model_paths
    plan = faults.FaultPlan([faults.FaultRule(
        point="registry.load", kind="transient", max_hits=2)], seed=0)
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        with faults.active(plan):
            entry = srv.load_model("m", pa)  # 2 failures, retried to ok
        assert entry.n_sv > 0
        assert plan.hits("registry.load") == 3


def test_failed_swap_rolls_back_and_recovers(model_paths, two_models):
    A, B = two_models
    pa, pb = model_paths
    Xq, _ = rings(n=8, seed=3)
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        srv.load_model("m", pa)
        srv.warmup()
        ref, _ = srv.predict_direct("m", Xq)
        # corrupt rule mangles the staged artifact's bytes mid-swap
        plan = faults.FaultPlan([faults.FaultRule(
            point="registry.load", kind="corrupt", at_hit=1)], seed=3)
        with faults.active(plan):
            with pytest.raises(ModelLoadError):
                srv.swap("m", pb)
        h = srv.health()
        assert h["status"] == "degraded"
        assert h["swap"]["m"]["last_swap"]["outcome"] == "failed"
        assert "error" in h["swap"]["m"]["last_swap"]
        assert srv.registry.generation("m") == 1
        s, _ = srv.predict_direct("m", Xq)
        assert np.array_equal(s, ref)  # the old generation, bitwise
        assert srv.metrics("m")["swap_failures"] == 1
        # a later clean swap clears the degraded flag
        srv.swap("m", pb)
        assert srv.health()["status"] == "ok"
        assert srv.registry.generation("m") == 2


def test_swap_killed_mid_stage_leaves_old_generation(model_paths):
    pa, pb = model_paths
    Xq, _ = rings(n=8, seed=3)
    plan = faults.FaultPlan([faults.FaultRule(
        point="serve.swap", kind="kill", at_hit=1)], seed=0)
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        srv.load_model("m", pa)
        srv.warmup()
        ref, _ = srv.predict_direct("m", Xq)
        with faults.active(plan):
            with pytest.raises(faults.SimulatedKill):
                srv.swap("m", pb)
        # nothing flipped, nothing recorded (a dead process records
        # nothing); serving continues on the old generation
        s, _ = srv.predict_direct("m", Xq)
        assert np.array_equal(s, ref)
        assert srv.registry.generation("m") == 1
        srv.swap("m", pb)  # and the server is not wedged
        assert srv.registry.generation("m") == 2


def test_swap_unknown_model_is_keyerror(model_paths):
    pa, _ = model_paths
    with Server(ServeConfig(max_batch=4)) as srv:
        with pytest.raises(KeyError, match="unknown model"):
            srv.swap("nope", pa)


# ----------------------------------------------------------- serve state
def test_serve_state_roundtrip_with_generations(model_paths):
    pa, pb = model_paths
    state = os.path.join(os.path.dirname(pa), "serve_state.json")
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as s1:
        s1.enable_state(state)
        s1.load_model("m", pa)
        s1.swap("m", pb)
        s1.swap("m", pa)
    obj = json.load(open(state))
    assert obj["format_version"] == 1
    assert obj["models"]["m"] == {"path": pa, "generation": 3}
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as s2:
        rep = s2.restore_state(state)
        assert rep["restored"] == ["m"] and rep["skipped"] == []
        assert s2.registry.generation("m") == 3
        e = s2.registry.get("m")
        assert e.source_path == pa


def test_serve_state_corrupt_is_named(tmp_path):
    p = str(tmp_path / "serve_state.json")
    with open(p, "w") as f:
        f.write("{not json")
    from tpusvm.serve.cache import load_serve_state

    with pytest.raises(ValueError, match="not valid JSON"):
        load_serve_state(p)
    with open(p, "w") as f:
        json.dump({"models": {}}, f)
    with pytest.raises(ValueError, match="format_version"):
        load_serve_state(p)


def test_cache_manifest_corrupt_is_tolerated(tmp_path):
    from tpusvm.obs.registry import default_registry
    from tpusvm.serve.cache import (
        CACHE_MANIFEST_NAME,
        read_cache_manifest,
        record_signatures,
    )

    d = str(tmp_path)
    m = record_signatures(d, ["binary:rbf:deg3:b8:blk8:d2:sv10:float32"])
    assert len(m["signatures"]) == 1
    m2 = read_cache_manifest(d)
    assert m2["signatures"] == m["signatures"]
    before = default_registry().counter(
        "serve.cache_manifest_invalid").value
    with open(os.path.join(d, CACHE_MANIFEST_NAME), "w") as f:
        f.write("garbage")
    m3 = read_cache_manifest(d)  # provenance, not truth: fresh manifest
    assert m3["signatures"] == {}
    assert default_registry().counter(
        "serve.cache_manifest_invalid").value == before + 1


def test_cache_read_fault_point_is_retried(tmp_path):
    from tpusvm.serve.cache import read_cache_manifest

    plan = faults.FaultPlan([faults.FaultRule(
        point="cache.read", kind="transient", max_hits=2)], seed=0)
    with faults.active(plan):
        m = read_cache_manifest(str(tmp_path))
    assert m["signatures"] == {}
    assert plan.hits("cache.read") == 3


# ---------------------------------------------------------------- watch
def test_watcher_loads_swaps_and_remembers_failures(two_models, tmp_path):
    from tpusvm.serve.watch import ModelWatcher

    A, B = two_models
    wdir = tmp_path / "watch"
    wdir.mkdir()
    logs = []
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        w = ModelWatcher(srv, str(wdir), log_fn=logs.append,
                         warmup=False)
        assert w.poll_once() == []  # empty dir: nothing to do
        p = str(wdir / "m.npz")
        A.save(p)
        acts = w.poll_once()
        assert [a["action"] for a in acts] == ["loaded"]
        assert "m" in srv.registry
        assert w.poll_once() == []  # unchanged mtime: no re-load
        # a newer artifact under the same stem hot-swaps
        time.sleep(0.02)
        B.save(p)
        os.utime(p, (time.time() + 1, time.time() + 1))
        acts = w.poll_once()
        assert [a["action"] for a in acts] == ["swapped"]
        assert srv.registry.generation("m") == 2
        # a corrupt artifact fails once and is NOT retried until it moves
        raw = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(raw[: len(raw) // 3])
        os.utime(p, (time.time() + 2, time.time() + 2))
        acts = w.poll_once()
        assert [a["action"] for a in acts] == ["failed"]
        assert "generation keeps serving" in logs[-1]
        assert w.poll_once() == []  # remembered: no hot loop
        assert srv.registry.generation("m") == 2  # old gen intact
        # fixed artifact with a newer mtime swaps again
        A.save(p)
        os.utime(p, (time.time() + 3, time.time() + 3))
        assert [a["action"] for a in w.poll_once()] == ["swapped"]
        assert srv.registry.generation("m") == 3


# -------------------------------------------------------------- refresh
def _fit_refresh_reference(deployed_path, X, Y, tmp_path, **kw):
    from tpusvm.serve.refresh import refresh_fit

    return refresh_fit(deployed_path, X, Y,
                       out_path=str(tmp_path / "ref_out.npz"), **kw)


def test_refresh_warm_start_saves_updates_and_serves(tmp_path):
    from tpusvm.serve.refresh import refresh_fit

    X, Y = rings(n=360, seed=11)
    deployed = str(tmp_path / "deployed.npz")
    BinarySVC(CFG_A).fit(X[:240], Y[:240]).save(deployed)
    warm = refresh_fit(deployed, X, Y,
                       out_path=str(tmp_path / "warm.npz"))
    cold = refresh_fit(deployed, X, Y, warm=False,
                       out_path=str(tmp_path / "cold.npz"))
    assert warm.status_.name == "CONVERGED"
    assert warm.n_iter_ < cold.n_iter_  # the warm seed does real work
    # the swapped-in artifact serves the refreshed model's exact bytes
    with Server(ServeConfig(max_batch=8)) as srv:
        srv.load_model("m", deployed)
        srv.warmup()
        out = srv.swap("m", str(tmp_path / "warm.npz"))
        assert out["generation"] == 2
        scores, _ = srv.predict_direct("m", X[:16])
        offline = BinarySVC.load(str(tmp_path / "warm.npz"),
                                 dtype=jnp.float32)
        assert np.array_equal(
            scores, np.asarray(offline.decision_function(X[:16])))


def test_refresh_kill_at_every_checkpoint_bit_identical(tmp_path):
    """The crash-safe-refresh acceptance claim: kill the refresh fit at
    EVERY solver checkpoint in turn, resume, and the resumed model —
    alphas, SV ids, b — is bit-identical to an uninterrupted refresh;
    the eventually-swapped model serves identical scores."""
    X, Y = rings(n=360, seed=11)
    deployed = str(tmp_path / "deployed.npz")
    BinarySVC(CFG_A).fit(X[:240], Y[:240]).save(deployed)
    # q=16 forces many outer rounds (the kill-resume smoke's shape) so
    # several checkpoints actually get written before convergence
    opts = {"q": 16}
    plain = _fit_refresh_reference(deployed, X, Y, tmp_path,
                                   solver_opts=opts)
    # every=1: the warm seed converges in a handful of outer rounds, so
    # a coarser cadence would write no checkpoint at all
    every = 1
    ck_ref = str(tmp_path / "ck_ref.npz")
    ckpted = _fit_refresh_reference(deployed, X, Y, tmp_path,
                                    solver_opts=opts,
                                    checkpoint_path=ck_ref,
                                    checkpoint_every=every)
    assert ckpted.sv_alpha_.tobytes() == plain.sv_alpha_.tobytes()
    assert np.array_equal(ckpted.sv_ids_, plain.sv_ids_)
    assert ckpted.b_ == plain.b_

    # kill at checkpoints 1..8 (kills past the last checkpoint simply
    # never fire — the uninterrupted run covers those); at least one
    # must fire for the test to mean anything
    killed_any = False
    for k in range(1, 7):
        ck = str(tmp_path / f"ck{k}.npz")
        plan = faults.FaultPlan([faults.FaultRule(
            point="solver.outer_checkpoint", kind="kill", at_hit=k)],
            seed=0)
        try:
            with faults.active(plan):
                _fit_refresh_reference(deployed, X, Y, tmp_path,
                                       solver_opts=opts,
                                       checkpoint_path=ck,
                                       checkpoint_every=every)
        except faults.SimulatedKill:
            killed_any = True
        else:
            continue  # solve finished before checkpoint k
        resumed = _fit_refresh_reference(deployed, X, Y, tmp_path,
                                         solver_opts=opts,
                                         checkpoint_path=ck,
                                         checkpoint_every=every,
                                         resume=True)
        assert resumed.sv_alpha_.tobytes() == plain.sv_alpha_.tobytes()
        assert np.array_equal(resumed.sv_ids_, plain.sv_ids_)
        assert resumed.b_ == plain.b_
    assert killed_any, "no checkpoint kill ever fired"


def test_refresh_rejects_wrong_artifacts(tmp_path):
    from tpusvm.serve.refresh import refresh_fit

    X, Y = rings(n=300, seed=7)
    approx = str(tmp_path / "approx.npz")
    BinarySVC(SVMConfig(C=10.0, gamma=10.0, kernel="rff",
                        rff_dim=128)).fit(X, Y).save(approx)
    with pytest.raises(ValueError, match="approximate primal"):
        refresh_fit(approx, X, Y, out_path=str(tmp_path / "o.npz"))


def test_deployed_seed_prefix_contract():
    from tpusvm.tune.warm import deployed_seed

    Y = np.array([1, -1, 1, -1, 1, -1])
    a = deployed_seed(np.array([0, 1]), np.array([2.0, 2.0]), 6, Y, 10.0)
    assert a.shape == (6,)
    assert a[0] == 2.0 and a[1] == 2.0 and not a[2:].any()
    assert float(np.sum(a * Y)) == 0.0
    with pytest.raises(ValueError, match="prefix"):
        deployed_seed(np.array([7]), np.array([1.0]), 6, Y, 10.0)


# ----------------------------------------------------------------- HTTP
def test_http_admin_swap_roundtrip(model_paths, two_models):
    import urllib.request

    from tpusvm.serve.http import make_http_server, start_http_thread
    from tpusvm.serve.refresh import swap_via_http

    pa, pb = model_paths
    A, B = two_models
    Xq, _ = rings(n=4, seed=5)
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        srv.load_model("m", pa)
        srv.warmup()
        httpd = make_http_server(srv, port=0)
        srv.attach_http(httpd, start_http_thread(httpd))
        port = httpd.server_address[1]
        base = f"http://127.0.0.1:{port}"
        out = swap_via_http(base, "m", pb)
        assert out["swapped"] is True and out["generation"] == 2
        scores, _ = srv.predict_direct("m", Xq)
        assert np.array_equal(
            scores, np.asarray(B.decision_function(Xq)))
        # healthz carries the swap block
        health = json.loads(
            urllib.request.urlopen(f"{base}/healthz").read())
        assert health["swap"]["m"]["generation"] == 2
        # unknown name -> 404 (named), bad artifact -> 409 + rollback
        with pytest.raises(RuntimeError, match="HTTP 404"):
            swap_via_http(base, "nope", pb)
        bad = pa + ".bad.npz"
        with open(bad, "wb") as f:
            f.write(b"not a zip")
        with pytest.raises(RuntimeError, match="HTTP 409"):
            swap_via_http(base, "m", bad)
        assert srv.registry.generation("m") == 2  # rolled back
        assert srv.health()["status"] == "degraded"


# --------------------------------------------------- committed artifacts
def test_committed_cold_start_artifact_gates():
    """The committed restart evidence must actually claim the win: the
    warm arm reports zero persistent-cache misses and bit-equal scores
    (regenerating a regressed artifact fails here, not just in CI)."""
    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        "results", "cold_start_cpu.jsonl")
    rows = [json.loads(line) for line in open(path) if line.strip()]
    by_arm = {r["arm"]: r for r in rows}
    assert set(by_arm) == {"cold", "warm"}
    warm, cold = by_arm["warm"], by_arm["cold"]
    assert warm["misses"] == 0 and warm["warm_ok"] is True
    assert warm["hits"] > 0
    assert cold["misses"] > 0  # the cold arm really was cold
    assert warm["score_parity"] is True
    assert warm["provenance"]["backend"] == "cpu"


def test_serve_status_carries_swap_fields(two_models):
    A, _ = two_models
    with Server(ServeConfig(max_batch=4), dtype=jnp.float64) as srv:
        srv.add_model("m", A)
        st = srv.status()["models"]["m"]
        assert st["generation"] == 1
        assert st["staleness_s"] >= 0
        assert st["last_swap"] is None
        assert st["source_path"] is None
        # gauges land in the metrics registry for /metrics + report
        snap = srv._worker("m").metrics.registry_snapshot()
        names = {e["name"] for e in snap["metrics"]}
        assert "serve.generation" in names
        assert "serve.staleness_s" in names
