"""Cascade SVM tests on the simulated 8-device CPU mesh.

The reference's correctness criterion for the cascades is recovery of the
serial solver's SV set and accuracy (SURVEY.md §4, §6: identical 1548 SVs at
every P for both variants). Here: both topologies, several shard counts, must
recover the oracle's SV ID set and b on synthetic data.
"""

import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from tpusvm.config import CascadeConfig, SVMConfig
from tpusvm.data import MinMaxScaler, blobs, rings
from tpusvm.oracle import get_sv_indices, smo_train
from tpusvm.parallel import cascade_fit

CFG = SVMConfig(C=10.0, gamma=10.0)


def _ring_data(n=512, seed=5):
    X, Y = rings(n=n, seed=seed)
    return MinMaxScaler().fit_transform(X), Y


@pytest.fixture(scope="module")
def oracle_rings():
    Xs, Y = _ring_data()
    o = smo_train(Xs, Y, CFG)
    return Xs, Y, o


@pytest.mark.parametrize("topology", ["tree", "star"])
@pytest.mark.parametrize("n_shards", [2, 8])
def test_cascade_recovers_oracle_sv_set(oracle_rings, topology, n_shards):
    Xs, Y, o = oracle_rings
    res = cascade_fit(
        Xs, Y, CFG,
        CascadeConfig(n_shards=n_shards, sv_capacity=256, topology=topology),
        dtype=jnp.float64,
    )
    assert res.converged
    assert set(res.sv_ids.tolist()) == set(get_sv_indices(o.alpha).tolist())
    np.testing.assert_allclose(res.b, o.b, atol=1e-4)
    # alphas of the converged global model match the oracle's on the SV set
    order = np.argsort(res.sv_ids)
    np.testing.assert_allclose(
        res.sv_alpha[order], o.alpha[np.sort(res.sv_ids)], atol=1e-3
    )


@pytest.mark.parametrize("topology,n_shards", [("tree", 4), ("star", 3)])
def test_cascade_blocked_solver_recovers_oracle(oracle_rings, topology, n_shards):
    # per-shard blocked working-set solver (the accelerated-solver-per-rank
    # hybrid): different iteration trajectory, same SV-set fixed point
    Xs, Y, o = oracle_rings
    res = cascade_fit(
        Xs, Y, CFG,
        CascadeConfig(n_shards=n_shards, sv_capacity=256, topology=topology),
        dtype=jnp.float64,
        solver="blocked",
        solver_opts={"q": 64},
    )
    assert res.converged
    assert set(res.sv_ids.tolist()) == set(get_sv_indices(o.alpha).tolist())
    np.testing.assert_allclose(res.b, o.b, atol=1e-4)


def test_cascade_unknown_solver_rejected():
    Xs, Y = _ring_data(n=64)
    with pytest.raises(ValueError, match="solver"):
        cascade_fit(Xs, Y, CFG, CascadeConfig(n_shards=2, topology="star"),
                    solver="newton")


def test_star_non_power_of_two_shards():
    # the classical tree requires P = 2^k (mpi_svm_main3.cpp:420-428) but the
    # star variant runs at any P
    Xs, Y = _ring_data()
    o = smo_train(Xs, Y, CFG)
    res = cascade_fit(
        Xs, Y, CFG,
        CascadeConfig(n_shards=3, sv_capacity=256, topology="star"),
        dtype=jnp.float64,
    )
    assert res.converged
    assert set(res.sv_ids.tolist()) == set(get_sv_indices(o.alpha).tolist())


def test_tree_requires_power_of_two():
    with pytest.raises(ValueError, match="power-of-two"):
        CascadeConfig(n_shards=3, topology="tree")


def test_unknown_topology_rejected():
    with pytest.raises(ValueError, match="topology"):
        CascadeConfig(topology="ring")


def test_empty_shards_are_harmless():
    # n chosen so trailing shards are entirely padding (partition cap=ceil)
    X, Y = blobs(n=130, seed=6)
    Xs = MinMaxScaler().fit_transform(X)
    cfg = SVMConfig(C=1.0, gamma=0.125)
    o = smo_train(Xs, Y, cfg)
    res = cascade_fit(
        Xs, Y, cfg,
        CascadeConfig(n_shards=8, sv_capacity=128, topology="star"),
        dtype=jnp.float64,
    )
    assert res.converged
    assert set(res.sv_ids.tolist()) == set(get_sv_indices(o.alpha).tolist())


def test_sv_capacity_overflow_raises():
    Xs, Y = _ring_data()
    with pytest.raises(RuntimeError, match="overflow"):
        cascade_fit(
            Xs, Y, CFG,
            CascadeConfig(n_shards=2, sv_capacity=4, topology="star"),
            dtype=jnp.float64,
        )


def test_star_merge_capacity_overflow_retries_full_width():
    # a layer-2 retrain buffer too small for the worker-SV union must NOT
    # silently truncate the merged problem: the round is re-run at the
    # full concatenation capacity (with a warning), and the result must
    # match an explicitly wide run
    Xs, Y = _ring_data()
    cc = dict(n_shards=2, sv_capacity=256, topology="star")
    with pytest.warns(RuntimeWarning, match="overflowed the star merge"):
        r_tight = cascade_fit(
            Xs, Y, CFG, CascadeConfig(**cc, star_merge_capacity=2),
            dtype=jnp.float64,
        )
    r_wide = cascade_fit(
        Xs, Y, CFG, CascadeConfig(**cc, star_merge_capacity=512),
        dtype=jnp.float64,
    )
    assert set(r_tight.sv_ids.tolist()) == set(r_wide.sv_ids.tolist())
    np.testing.assert_allclose(r_tight.b, r_wide.b, atol=1e-9)


def test_star_merge_capacity_rejected_for_tree():
    with pytest.raises(ValueError, match="star_merge_capacity"):
        CascadeConfig(n_shards=2, topology="tree", star_merge_capacity=64)


def test_star_merge_capacity_default_is_overflow_proof_bound():
    # VERDICT r4 #7: the default layer-2 capacity is the structural
    # concatenation bound P*sv_capacity (rank 0's merged set in the
    # reference is P worker-sized sets, mpi_svm_main2.cpp:540-621), so the
    # zero-config path can never overflow-and-recompile mid-fit
    cc = CascadeConfig(n_shards=4, sv_capacity=256, topology="star")
    assert cc.resolved_star_merge_capacity() == 4 * 256
    cc2 = CascadeConfig(n_shards=8, sv_capacity=32, topology="star")
    assert cc2.resolved_star_merge_capacity() == 8 * 32


def test_star_merge_capacity_tight_matches_wide_buffer():
    # an explicitly TIGHT layer-2 capacity must not change the cascade's
    # outcome vs the overflow-proof default (padding is masked out of the
    # solve either way). n_shards=4 / tight=512 vs default 4*256=1024.
    Xs, Y = _ring_data()
    cc = dict(n_shards=4, sv_capacity=256, topology="star")
    # error on RuntimeWarning: if the union ever outgrew the tight value
    # the run would silently widen to full capacity and this test would
    # degrade to wide-vs-wide; fail loudly instead
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        r_tight = cascade_fit(
            Xs, Y, CFG, CascadeConfig(**cc, star_merge_capacity=512),
            dtype=jnp.float64,
        )
    r_wide = cascade_fit(Xs, Y, CFG, CascadeConfig(**cc),
                         dtype=jnp.float64)
    assert set(r_tight.sv_ids.tolist()) == set(r_wide.sv_ids.tolist())
    # b: the padded-axis reduction order differs between buffer widths, so
    # the SMO trajectory may take a different path inside the tau=1e-5
    # stopping band — same contract as the reference's cross-implementation
    # parity (b agreement to 0.003%, SURVEY.md §4), not bit-exactness
    np.testing.assert_allclose(r_tight.b, r_wide.b, atol=1e-4)


@pytest.mark.parametrize("topology", ["tree", "star"])
def test_single_shard_cascade_degenerates_cleanly(oracle_rings, topology):
    # P=1 is the single-accelerator edge (one real chip, no partner to
    # merge with): both topologies must run their collective machinery
    # over the 1-member mesh and converge to the plain solve's SV set in
    # the minimum 2 rounds (solve, then ID-set-stable confirmation)
    Xs, Y, o = oracle_rings
    r = cascade_fit(
        Xs, Y, CFG,
        CascadeConfig(n_shards=1, sv_capacity=256, topology=topology),
        dtype=jnp.float64,
    )
    assert r.converged and r.rounds == 2
    assert set(r.sv_ids.tolist()) == set(get_sv_indices(o.alpha).tolist())
    np.testing.assert_allclose(r.b, o.b, atol=1e-4)


def test_history_diagnostics():
    Xs, Y = _ring_data()
    res = cascade_fit(
        Xs, Y, CFG,
        CascadeConfig(n_shards=2, sv_capacity=256, topology="tree"),
        dtype=jnp.float64,
    )
    assert res.rounds == len(res.history)
    h0 = res.history[0]
    assert h0["round"] == 1 and h0["sv_count"] > 0 and h0["time_s"] > 0
    # per-device, per-step solver iteration counts are recorded
    assert h0["iters"].shape[0] == 2
    # per-round SV-ID snapshots (sorted, consistent with the count) power
    # the Fig. 6 round-1-fraction statistic in benchmarks/sweep_p.py
    for h in res.history:
        assert len(h["sv_ids"]) == h["sv_count"]
        assert (np.diff(h["sv_ids"]) > 0).all()
    # the last round's snapshot IS the final model's SV-ID set
    np.testing.assert_array_equal(
        res.history[-1]["sv_ids"], np.sort(res.sv_ids)
    )


def test_label_sorted_data_raises_not_nan():
    # every shard single-class -> no working set anywhere; must fail loudly
    # instead of returning an empty model with b = NaN
    X, Y = blobs(n=128, seed=9)
    order = np.argsort(Y)
    with pytest.raises(RuntimeError, match="empty global support-vector set"):
        cascade_fit(
            X[order], Y[order], SVMConfig(C=1.0, gamma=0.125),
            CascadeConfig(n_shards=2, sv_capacity=64, topology="star"),
            dtype=jnp.float64,
        )


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_cascade_randomized_geometry_recovers_oracle(seed):
    """Breadth: random blob geometry through both topologies must land on
    the oracle's SV-ID fixed point (the reference's every-P parity claim,
    README.md:35-38), complementing the targeted rings cases above."""
    cfg = SVMConfig(C=10.0, gamma=2.0)
    X, Y = blobs(n=256, d=6, seed=seed)
    Xs = MinMaxScaler().fit_transform(X)
    o = smo_train(Xs, Y, cfg)
    sv_o = set(get_sv_indices(o.alpha).tolist())
    for topology, n_shards in (("tree", 4), ("star", 5)):
        res = cascade_fit(
            Xs, Y, cfg,
            CascadeConfig(n_shards=n_shards, sv_capacity=192,
                          topology=topology),
            dtype=jnp.float64,
        )
        assert res.converged, (topology, seed)
        assert set(res.sv_ids.tolist()) == sv_o, (topology, seed)
        np.testing.assert_allclose(res.b, o.b, atol=1e-4)
