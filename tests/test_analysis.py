"""Tests for tpusvm.analysis — the JAX tracing-safety & TPU-hazard linter.

Three contracts:
  * every rule JX001-JX008 fires on its known-bad corpus snippet
    (tests/analysis_corpus/) and stays quiet on the known-good one;
  * the repo's own trees lint clean (modulo the checked-in baseline) —
    the CI gate, run in-process here so a regression fails tier-1 too;
  * the CLI surface is stable: JSON reporter schema, suppression
    comments, baseline round-trip, exit codes.

The linter is pure stdlib ast (no JAX import), so these tests are cheap.
"""

import json
from pathlib import Path

import pytest

from tpusvm.analysis import all_rules, lint_file, lint_paths, lint_source

REPO = Path(__file__).resolve().parent.parent
CORPUS = REPO / "tests" / "analysis_corpus"
RULE_IDS = ("JX001", "JX002", "JX003", "JX004", "JX005", "JX006",
            "JX007", "JX008", "JX009", "JX010")


# ---------------------------------------------------------------- registry
def test_registry_has_all_rules():
    rules = all_rules()
    assert tuple(sorted(rules)) == RULE_IDS
    for rid, rule in rules.items():
        assert rule.id == rid
        assert rule.summary


def test_registry_rejects_unknown_select():
    from tpusvm.analysis.registry import select_rules

    with pytest.raises(ValueError, match="unknown rule"):
        select_rules(select={"JX999"})


# ------------------------------------------------------------------ corpus
@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_rule_fires_on_its_corpus_snippet(rule_id):
    matches = sorted(CORPUS.glob(f"{rule_id.lower()}_*.py"))
    assert matches, f"no corpus file for {rule_id}"
    findings, _ = lint_file(matches[0])
    fired = {f.rule for f in findings}
    assert rule_id in fired, (
        f"{rule_id} did not fire on {matches[0].name}; got {fired}"
    )
    # corpus snippets are single-hazard by construction: no OTHER rule
    # may fire, so a precision regression in any rule shows up here
    assert fired == {rule_id}, (
        f"extra rules fired on {matches[0].name}: {fired - {rule_id}}"
    )


def test_clean_corpus_is_clean():
    findings, suppressed = lint_file(CORPUS / "clean.py")
    assert findings == []
    assert suppressed == []


def test_every_corpus_finding_is_located():
    for f in CORPUS.glob("jx*.py"):
        findings, _ = lint_file(f)
        for finding in findings:
            assert finding.line >= 1 and finding.col >= 1
            assert finding.snippet  # points at real source text
            assert finding.fingerprint and len(finding.fingerprint) == 12


# ----------------------------------------------------------- repo is clean
def test_repo_lints_clean():
    """The CI gate, in-process: tpusvm/ + benchmarks/ + scripts/ + bench.py
    produce zero unsuppressed findings (this repo carries no baseline
    entries — deliberate syncs are annotated inline where they live)."""
    result = lint_paths([str(REPO / "tpusvm"), str(REPO / "benchmarks"),
                         str(REPO / "scripts"), str(REPO / "bench.py")])
    assert result.findings == [], "\n".join(
        f.render() for f in result.findings
    )
    assert result.files_scanned > 50  # the walk actually found the tree


def test_corpus_excluded_from_directory_walk():
    # linting tests/ must NOT pick up the known-bad corpus
    result = lint_paths([str(REPO / "tests")])
    corpus_paths = {f.path for f in result.findings
                    if "analysis_corpus" in f.path}
    assert corpus_paths == set()


# ------------------------------------------------------------- suppression
def test_inline_suppression():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x.sum() > 0:  # tpusvm: disable=JX001\n"
        "        x = -x\n"
        "    return x\n"
    )
    findings, suppressed = lint_source(src)
    assert findings == []
    assert [f.rule for f in suppressed] == ["JX001"]


def test_standalone_comment_suppression_and_disable_all():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    # tpusvm: disable=all\n"
        "    if x.sum() > 0:\n"
        "        x = -x\n"
        "    return x\n"
    )
    findings, suppressed = lint_source(src)
    assert findings == [] and len(suppressed) == 1


def test_file_level_suppression():
    src = (
        "# tpusvm: disable-file=JX001\n"
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x.sum() > 0:\n"
        "        x = -x\n"
        "    return x\n"
    )
    findings, suppressed = lint_source(src)
    assert findings == [] and len(suppressed) == 1


def test_suppression_is_rule_specific():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x.sum() > 0:  # tpusvm: disable=JX002\n"
        "        x = -x\n"
        "    return x\n"
    )
    findings, _ = lint_source(src)
    assert [f.rule for f in findings] == ["JX001"]


# ------------------------------------------------------------ syntax error
def test_parse_failure_is_a_finding():
    findings, _ = lint_source("def broken(:\n")
    assert [f.rule for f in findings] == ["JX000"]
    assert "does not parse" in findings[0].message


# ---------------------------------------------------------------- baseline
def test_baseline_roundtrip(tmp_path):
    from tpusvm.analysis.baseline import load_baseline, write_baseline

    target = CORPUS / "jx001_tracer_branch.py"
    findings, _ = lint_file(target)
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(bl_path, findings)
    baseline = load_baseline(bl_path)
    assert len(baseline) == len(findings)
    result = lint_paths([str(target)], baseline=baseline)
    assert result.findings == []
    assert len(result.baselined) == len(findings)
    assert result.exit_code == 0


def test_baseline_missing_file_is_empty(tmp_path):
    from tpusvm.analysis.baseline import load_baseline

    assert load_baseline(tmp_path / "nope.json") == set()


def test_baseline_rejects_unknown_version(tmp_path):
    from tpusvm.analysis.baseline import load_baseline

    p = tmp_path / "bad.json"
    p.write_text('{"version": 99, "findings": []}')
    with pytest.raises(ValueError, match="baseline version"):
        load_baseline(p)


def test_fingerprint_survives_line_drift():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x.sum() > 0:\n"
        "        x = -x\n"
        "    return x\n"
    )
    f1, _ = lint_source(src)
    shifted = "# a new comment line\n" + src
    f2, _ = lint_source(shifted)
    assert f1[0].fingerprint == f2[0].fingerprint
    assert f1[0].line != f2[0].line


# --------------------------------------------------------------------- CLI
def test_cli_json_report_schema(capsys):
    from tpusvm.analysis.cli import main

    rc = main([str(CORPUS / "jx003_dynamic_shape.py"), "--format", "json",
               "--no-baseline"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert doc["tool"] == "tpusvm.analysis"
    assert doc["files_scanned"] == 1
    assert set(doc["rules"]) == set(RULE_IDS)
    assert isinstance(doc["suppressed"], int)
    assert isinstance(doc["baselined"], int)
    assert doc["counts"]["JX003"] == len(doc["findings"])
    for finding in doc["findings"]:
        assert set(finding) == {"rule", "path", "line", "col", "message",
                                "snippet", "fingerprint"}
        assert finding["rule"] == "JX003"
        assert isinstance(finding["line"], int)


def test_cli_clean_exit_zero(capsys):
    from tpusvm.analysis.cli import main

    rc = main([str(CORPUS / "clean.py"), "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "clean" in out


def test_cli_select_restricts_rules(capsys):
    from tpusvm.analysis.cli import main

    rc = main([str(CORPUS / "jx001_tracer_branch.py"),
               "--select", "JX007", "--no-baseline"])
    capsys.readouterr()
    assert rc == 0  # JX001 snippet is clean under a JX007-only run


def test_cli_unknown_path_is_usage_error(capsys):
    from tpusvm.analysis.cli import main

    rc = main(["definitely/not/a/path.py"])
    assert rc == 2


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    from tpusvm.analysis.cli import main

    bl = tmp_path / "bl.json"
    target = str(CORPUS / "jx004_dtype_drift.py")
    assert main([target, "--baseline", str(bl), "--write-baseline"]) == 0
    capsys.readouterr()
    assert main([target, "--baseline", str(bl)]) == 0
    out = capsys.readouterr().out
    assert "in baseline" in out


def test_cli_list_rules(capsys):
    from tpusvm.analysis.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULE_IDS:
        assert rid in out


# ----------------------------------------------- shared flag table (JX008)
def test_pallas_flag_table_matches_solver_kwargs():
    """Every pallas_* kwarg of blocked_smo_solve has a row in the shared
    flag-compatibility table, so a new flag cannot dodge validation."""
    import inspect

    from tpusvm.config import PALLAS_FLAG_RULES
    from tpusvm.solver.blocked import blocked_smo_solve

    sig = inspect.signature(blocked_smo_solve.__wrapped__)
    pallas_kwargs = {n for n in sig.parameters if n.startswith("pallas_")}
    assert pallas_kwargs == set(PALLAS_FLAG_RULES)
    # and the declared inactive values ARE the solver's defaults
    for name, spec in PALLAS_FLAG_RULES.items():
        assert sig.parameters[name].default == spec["inactive"], name


def test_pallas_flag_errors_helper():
    from tpusvm.config import pallas_flag_errors

    # inactive values never error, any engine
    assert pallas_flag_errors("xla", 1, {"pallas_multipair": 1,
                                         "pallas_eta_exclude": False,
                                         "pallas_layout": "packed"}) == []
    # active flag on a non-pallas engine
    errs = pallas_flag_errors("xla", 2, {"pallas_eta_exclude": True})
    assert len(errs) == 1 and "pallas-engine feature" in errs[0]
    # wss mismatch on the right engine
    errs = pallas_flag_errors("pallas", 1, {"pallas_eta_exclude": True})
    assert len(errs) == 1 and "requires wss=2" in errs[0]
    # unknown dimensions are skipped (static analysis knows only literals)
    assert pallas_flag_errors(None, None, {"pallas_multipair": 4}) == []


# --------------------------------------------- satellite regression guards
def test_midscale_effective_cfg_does_not_mutate_module_config():
    # conftest already pins CPU + x64, so the module import is side-effect
    # compatible with the rest of the suite
    from benchmarks.midscale_parity import CFG, effective_cfg

    before = CFG.max_iter
    cfg = effective_cfg(123)
    assert cfg.max_iter == 123
    assert CFG.max_iter == before  # the module global is untouched
    assert effective_cfg(None) is CFG


# ------------------------------------------------------- CI sweep coverage
def test_ci_lint_sweep_covers_all_roots():
    """The CI lint step must sweep every Python root the repo grows code
    in — tpusvm/, benchmarks/ and scripts/ (plus the bench.py harness).
    A root missing from the workflow would let hazards land unlinted; a
    legacy finding in a newly-added root belongs in the fingerprinted
    baseline, never in a narrower sweep."""
    ci = (REPO / ".github" / "workflows" / "ci.yml").read_text(
        encoding="utf-8")
    lint_lines = [ln for ln in ci.splitlines()
                  if "python -m tpusvm.analysis" in ln
                  and "ir-audit" not in ln
                  and "analysis conc" not in ln
                  and "analysis dura" not in ln]
    assert lint_lines, "CI has no tpusvm-lint invocation"
    sweep = " ".join(lint_lines)
    for root in ("tpusvm/", "benchmarks/", "scripts/", "bench.py"):
        assert root in sweep, (
            f"CI lint sweep is missing the {root} root: {sweep!r}")
    # the concurrency linter (tpusvm/analysis/conc) sweeps the SAME
    # roots — a root added to one sweep but not the other would let
    # threading hazards land unlinted (test_conc.py pins the rest of
    # the conc CI wiring)
    conc_lines = [ln for ln in ci.splitlines()
                  if "tpusvm.analysis conc " in ln]
    conc_sweep = " ".join(conc_lines)
    for root in ("tpusvm/", "benchmarks/", "scripts/", "bench.py"):
        assert root in conc_sweep, (
            f"CI conc sweep is missing the {root} root: {conc_sweep!r}")
    # and the durability linter (tpusvm/analysis/dura) sweeps the SAME
    # roots again — a root missing here would let unstaged final-path
    # writes land unlinted (test_dura.py pins the rest of the dura CI
    # wiring, including the derived crash-window matrix smoke)
    dura_lines = [ln for ln in ci.splitlines()
                  if "tpusvm.analysis dura " in ln]
    dura_sweep = " ".join(dura_lines)
    for root in ("tpusvm/", "benchmarks/", "scripts/", "bench.py"):
        assert root in dura_sweep, (
            f"CI dura sweep is missing the {root} root: {dura_sweep!r}")


def test_ci_self_corpus_expects_every_rule():
    """The CI self-corpus step's expected-rule set must track the
    registry — a rule added without a corpus case (or a corpus case the
    CI never asserts on) silently weakens the gate."""
    ci = (REPO / ".github" / "workflows" / "ci.yml").read_text(
        encoding="utf-8")
    # the step derives its expected set from the registry, so it cannot
    # lag RULE_IDS; this pins that derivation (and the corpus walk)
    assert "set(all_rules()) - fired" in ci
    assert 'glob("tests/analysis_corpus/*.py")' in ci
    # ... and the in-process registry actually covers RULE_IDS
    assert tuple(sorted(all_rules())) == RULE_IDS


def test_jx010_scope_exempts_contraction_homes():
    from tpusvm.analysis.lint import lint_source

    src = ("import jax\nimport jax.numpy as jnp\n"
           "@jax.jit\ndef f(a, b):\n    return a @ b\n")
    # same source: flagged outside the home modules, exempt inside
    active, _ = lint_source(src, "tpusvm/solver/somefile.py",
                            select={"JX010"})
    assert {f.rule for f in active} == {"JX010"}
    for home in ("tpusvm/ops/x.py", "tpusvm/kernels/x.py"):
        active, _ = lint_source(src, home, select={"JX010"})
        assert active == []
    # host-side NumPy `@` (no tracing context) is not flagged
    host = "import numpy as np\ndef f(a, b):\n    return a @ b\n"
    active, _ = lint_source(host, "tpusvm/oracle/x.py", select={"JX010"})
    assert active == []
